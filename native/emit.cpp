// Native GIL-free columnar emit: finished sink wire payloads straight
// from the flush arrays.
//
// The Python emit tier costs ~µs of dict/format work per emitted metric
// under the GIL, which timeslices against ingest on shared cores
// (PERF_MODEL.md cadence decomposition). Every serializer here is a
// single C-speed pass over the ColumnarMetrics buffers — the \x1e-joined
// meta blob ("name \x1f tag \x1f ..." records, the same fragments the
// forward encoder uses) plus dense f64 value / u8 mask planes — called
// through ctypes, so the GIL is released for the whole body build.
//
// Emitters:
//   vn_encode_datadog_series       chunked {"series":[...]} JSON bodies
//   vn_encode_signalfx_body        {"counter":[...],"gauge":[...]} body
//   vn_encode_prometheus_lines     statsd-repeater lines (sanitized)
//   vn_encode_forward_lines        DogStatsD forward lines (verbatim)
//   vn_encode_prometheus_exposition  exposition text (pushgateway)
//   vn_deflate / vn_deflate_chunks zlib deflate (== Python zlib.compress)
//
// Output is pinned byte-identical to the sinks' Python formatters by
// tests/test_emit_parity.py. Buffers are thread-local: a result is valid
// until the calling thread's next call into the same emitter.

#include <zlib.h>

#include <algorithm>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

void json_escape_append(std::string* out, std::string_view s) {
  for (unsigned char c : s) {
    switch (c) {
      case '"':
        out->append("\\\"");
        break;
      case '\\':
        out->append("\\\\");
        break;
      case '\b':
        out->append("\\b");
        break;
      case '\f':
        out->append("\\f");
        break;
      case '\n':
        out->append("\\n");
        break;
      case '\r':
        out->append("\\r");
        break;
      case '\t':
        out->append("\\t");
        break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof buf, "\\u%04x", c);
          out->append(buf);
        } else {
          out->push_back(static_cast<char>(c));
        }
    }
  }
}

void json_number_append(std::string* out, double v) {
  // shortest round-trip via std::to_chars (like python repr); JSON
  // forbids NaN/Inf — the python path emits null too (parity), keeping
  // the body valid
  if (!std::isfinite(v)) {
    out->append("null");
    return;
  }
  char buf[32];
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
  auto res = std::to_chars(buf, buf + sizeof buf, v);
  out->append(buf, static_cast<size_t>(res.ptr - buf));
#else
  // libstdc++ < 11 has no floating-point to_chars: emulate its
  // shortest-CHARACTERS round-trip guarantee by scanning %g precisions
  // and keeping the shortest string that reads back equal (minimal
  // precision alone is wrong — %.1g renders 20.0 as "2e+01", while
  // to_chars and the emitters' plain-int detection expect "20")
  int best = -1;
  char bestbuf[32];
  for (int prec = 1; prec <= 17; ++prec) {
    int n = snprintf(buf, sizeof buf, "%.*g", prec, v);
    if (n > 0 && n < static_cast<int>(sizeof buf) &&
        strtod(buf, nullptr) == v && (best < 0 || n < best)) {
      best = n;
      memcpy(bestbuf, buf, static_cast<size_t>(n));
    }
  }
  if (best < 0) {
    best = snprintf(bestbuf, sizeof bestbuf, "%.17g", v);
  }
  out->append(bestbuf, static_cast<size_t>(best));
#endif
}

// str(float) semantics for the line-oriented emitters, pinned
// byte-identical to CPython's float repr (the Python formatters print
// values with f-strings): find the SHORTEST significant-digit count
// that round-trips (scan correctly-rounded %.*e — the minimal p that
// reads back equal is exactly repr's digit string), then apply
// CPython's notation rule: fixed for -4 <= exp10 < 16 (integral values
// carry ".0"), otherwise scientific with a 2-digit signed exponent.
// NOTE deliberately NOT shortest-STRING (std::to_chars / a %g scan):
// those render 1e5 as "1e+05" where CPython prints "100000.0".
void py_float_append(std::string* out, double v) {
  if (std::isnan(v)) {
    out->append("nan");
    return;
  }
  if (std::isinf(v)) {
    out->append(v > 0 ? "inf" : "-inf");
    return;
  }
  if (v == 0.0) {
    out->append(std::signbit(v) ? "-0.0" : "0.0");
    return;
  }
  char buf[40];
  int prec = 17;
  for (int p = 1; p <= 17; ++p) {
    snprintf(buf, sizeof buf, "%.*e", p - 1, v);
    if (strtod(buf, nullptr) == v) {
      prec = p;
      break;
    }
  }
  snprintf(buf, sizeof buf, "%.*e", prec - 1, v);
  // parse "d.dddde±XX" back into digits + exponent
  char digits[24];
  int ndig = 0;
  int exp10 = 0;
  bool neg = false;
  for (const char* c = buf; *c; ++c) {
    if (*c == '-' && ndig == 0 && !neg) {
      neg = true;
    } else if (*c >= '0' && *c <= '9') {
      digits[ndig++] = *c;
    } else if (*c == 'e' || *c == 'E') {
      exp10 = static_cast<int>(strtol(c + 1, nullptr, 10));
      break;
    }
  }
  if (neg) out->push_back('-');
  if (exp10 >= -4 && exp10 < 16) {
    if (exp10 >= ndig - 1) {
      // integral: all digits, zero-pad, ".0"
      out->append(digits, static_cast<size_t>(ndig));
      out->append(static_cast<size_t>(exp10 - (ndig - 1)), '0');
      out->append(".0");
    } else if (exp10 >= 0) {
      out->append(digits, static_cast<size_t>(exp10 + 1));
      out->push_back('.');
      out->append(digits + exp10 + 1,
                  static_cast<size_t>(ndig - exp10 - 1));
    } else {
      out->append("0.");
      out->append(static_cast<size_t>(-exp10 - 1), '0');
      out->append(digits, static_cast<size_t>(ndig));
    }
  } else {
    out->push_back(digits[0]);
    if (ndig > 1) {
      out->push_back('.');
      out->append(digits + 1, static_cast<size_t>(ndig - 1));
    }
    out->push_back('e');
    out->push_back(exp10 < 0 ? '-' : '+');
    int ae = exp10 < 0 ? -exp10 : exp10;
    if (ae < 10) out->push_back('0');
    snprintf(buf, sizeof buf, "%d", ae);
    out->append(buf);
  }
}

// Prometheus exposition sample values: the format's own non-finite
// literals, otherwise str(float)
void expo_value_append(std::string* out, double v) {
  if (std::isnan(v)) {
    out->append("NaN");
    return;
  }
  if (std::isinf(v)) {
    out->append(v > 0 ? "+Inf" : "-Inf");
    return;
  }
  py_float_append(out, v);
}

std::vector<std::string_view> split_us(std::string_view blob) {
  std::vector<std::string_view> out;
  if (blob.empty()) return out;
  size_t pos = 0;
  for (;;) {
    size_t e = blob.find('\x1f', pos);
    if (e == std::string_view::npos) {
      out.push_back(blob.substr(pos));
      return out;
    }
    out.push_back(blob.substr(pos, e - pos));
    pos = e + 1;
  }
}

std::vector<std::string_view> split_rs(std::string_view blob,
                                       long long nrows) {
  std::vector<std::string_view> recs;
  recs.reserve(static_cast<size_t>(nrows));
  size_t pos = 0;
  for (long long i = 0; i < nrows; ++i) {
    size_t e = blob.find('\x1e', pos);
    if (e == std::string_view::npos) e = blob.size();
    recs.push_back(blob.substr(pos, e - pos));
    pos = e + 1;
  }
  return recs;
}

struct DDOut {
  std::string buf;
  std::vector<long long> chunk_off;
};
thread_local DDOut g_dd;

}  // namespace

extern "C" {

// Emits n_chunks bodies, each a complete {"series":[...]} JSON object
// of at most max_per_body entries, concatenated in one buffer with
// chunk offsets ([n_chunks+1]). Buffers are thread-local (valid until
// the calling thread's next call). Returns n_chunks, or -1 on
// malformed meta.
long long vn_encode_datadog_series(
    const char* meta, long long meta_len, long long nrows,
    const char* suffixes_blob, long long suffixes_len,
    const signed char* family_types, int nfam, const double* values,
    const unsigned char* masks, long long ts, double interval,
    const char* hostname, long long hostname_len, const char* common,
    long long common_len, const char* excl_keys_blob,
    long long excl_keys_len, const char* excl_prefix_blob,
    long long excl_prefix_len, const char* drop_prefix_blob,
    long long drop_prefix_len, long long max_per_body,
    const long long** chunk_off_out, const char** out,
    long long* out_len, long long* entries_out) {
  DDOut& o = g_dd;
  o.buf.clear();
  o.chunk_off.clear();
  o.buf.reserve(static_cast<size_t>(nrows) * nfam * 96);

  std::vector<std::string_view> suffixes =
      split_us(std::string_view(suffixes_blob,
                                static_cast<size_t>(suffixes_len)));
  // empty suffixes vanish in the join; pad back to nfam
  while (static_cast<int>(suffixes.size()) < nfam)
    suffixes.push_back(std::string_view());
  std::vector<std::string_view> excl_keys = split_us(
      std::string_view(excl_keys_blob, static_cast<size_t>(excl_keys_len)));
  std::vector<std::string_view> excl_prefixes = split_us(std::string_view(
      excl_prefix_blob, static_cast<size_t>(excl_prefix_len)));
  std::vector<std::string_view> drop_prefixes = split_us(std::string_view(
      drop_prefix_blob, static_cast<size_t>(drop_prefix_len)));
  std::string_view host_default(hostname,
                                static_cast<size_t>(hostname_len));
  std::string_view common_frag(common, static_cast<size_t>(common_len));

  // pre-split the meta records once
  std::vector<std::string_view> recs = split_rs(
      std::string_view(meta, static_cast<size_t>(meta_len)), nrows);

  char interval_buf[24];
  std::snprintf(interval_buf, sizeof interval_buf, "%lld",
                static_cast<long long>(interval));

  long long in_chunk = 0;
  long long entries_total = 0;
  bool chunk_open = false;
  auto open_chunk = [&]() {
    o.chunk_off.push_back(static_cast<long long>(o.buf.size()));
    o.buf.append("{\"series\":[");
    in_chunk = 0;
    chunk_open = true;
  };
  auto close_chunk = [&]() {
    if (chunk_open) {
      o.buf.append("]}");
      chunk_open = false;
    }
  };

  std::string tag_scratch;
  for (int f = 0; f < nfam; ++f) {
    std::string_view suffix = suffixes[f];
    bool is_rate = family_types[f] == 0;
    const double* vals = values + static_cast<size_t>(f) * nrows;
    const unsigned char* mask = masks + static_cast<size_t>(f) * nrows;
    for (long long r = 0; r < nrows; ++r) {
      if (!mask[r]) continue;
      std::string_view rec = recs[static_cast<size_t>(r)];
      size_t nend = rec.find('\x1f');
      std::string_view name =
          nend == std::string_view::npos ? rec : rec.substr(0, nend);
      // name drops apply to the FULL emitted name (base + suffix); the
      // python path checks m.name which already carries the suffix
      bool dropped = false;
      for (std::string_view p : drop_prefixes) {
        if (name.size() >= p.size() &&
            name.compare(0, p.size(), p) == 0) {
          dropped = true;
          break;
        }
        // suffix may complete the prefix match only if prefix is
        // longer than the base name; rare — handle by building the
        // full name check below when p is longer
        if (p.size() > name.size()) {
          std::string full(name);
          full.append(suffix);
          if (full.compare(0, p.size(), p) == 0) {
            dropped = true;
            break;
          }
        }
      }
      if (dropped) continue;

      // tags: host/device extraction + exclusions
      std::string_view host = host_default;
      std::string_view device;
      tag_scratch.clear();
      if (nend != std::string_view::npos) {
        std::string_view rest = rec.substr(nend + 1);
        for (;;) {
          size_t e = rest.find('\x1f');
          std::string_view tag =
              e == std::string_view::npos ? rest : rest.substr(0, e);
          // server-level key exclusion removes the tag before the sink
          // ever sees it (strip_excluded_tags runs first on the Python
          // paths) — including before host:/device: extraction
          bool skip = false;
          {
            size_t colon = tag.find(':');
            std::string_view key =
                colon == std::string_view::npos ? tag
                                                : tag.substr(0, colon);
            for (std::string_view k : excl_keys) {
              if (key == k) {
                skip = true;
                break;
              }
            }
          }
          if (!skip) {
            if (tag.size() >= 5 && tag.compare(0, 5, "host:") == 0) {
              if (tag.size() > 5) host = tag.substr(5);
              skip = true;
            } else if (tag.size() >= 7 &&
                       tag.compare(0, 7, "device:") == 0) {
              device = tag.substr(7);
              skip = true;
            }
          }
          if (!skip) {
            for (std::string_view p : excl_prefixes) {
              if (tag.size() >= p.size() &&
                  tag.compare(0, p.size(), p) == 0) {
                skip = true;
                break;
              }
            }
          }
          if (!skip) {
            tag_scratch.push_back(',');
            tag_scratch.push_back('"');
            json_escape_append(&tag_scratch, tag);
            tag_scratch.push_back('"');
          }
          if (e == std::string_view::npos) break;
          rest = rest.substr(e + 1);
        }
      }

      if (!chunk_open) open_chunk();
      if (in_chunk) o.buf.push_back(',');
      o.buf.append("{\"metric\":\"");
      json_escape_append(&o.buf, name);
      json_escape_append(&o.buf, suffix);
      o.buf.append("\",\"points\":[[");
      char tsbuf[24];
      std::snprintf(tsbuf, sizeof tsbuf, "%lld", ts);
      o.buf.append(tsbuf);
      o.buf.push_back(',');
      json_number_append(&o.buf,
                         is_rate ? vals[r] / interval : vals[r]);
      o.buf.append("]],\"tags\":[");
      bool any_common = common_frag.size() > 0;
      if (any_common) o.buf.append(common_frag);
      if (!tag_scratch.empty()) {
        if (any_common)
          o.buf.append(tag_scratch);  // starts with ','
        else
          o.buf.append(tag_scratch.data() + 1, tag_scratch.size() - 1);
      }
      o.buf.append("],\"type\":\"");
      o.buf.append(is_rate ? "rate" : "gauge");
      o.buf.append("\",\"interval\":");
      o.buf.append(interval_buf);
      o.buf.append(",\"host\":\"");
      json_escape_append(&o.buf, host);
      o.buf.append("\",\"device_name\":\"");
      json_escape_append(&o.buf, device);
      o.buf.append("\"}");
      ++in_chunk;
      ++entries_total;
      if (in_chunk >= max_per_body) close_chunk();
    }
  }
  close_chunk();
  o.chunk_off.push_back(static_cast<long long>(o.buf.size()));
  *entries_out = entries_total;
  *chunk_off_out = o.chunk_off.data();
  *out = o.buf.data();
  *out_len = static_cast<long long>(o.buf.size());
  return static_cast<long long>(o.chunk_off.size()) - 1;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// statsd line emitters: the prometheus statsd-repeater path (exporter
// character sanitization) and the DogStatsD forward path (verbatim
// names/tags a downstream veneur re-ingests) share one line builder.

namespace {

inline bool prom_name_ok(unsigned char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '_' || c == ':' || c == '.';
}

inline bool prom_tag_ok(unsigned char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '_' || c == ':' || c == ',' ||
         c == '=' || c == '.';
}

// Sanitize like the sinks' Python regexes do: one '_' per CHARACTER
// outside the accepted set. Input is UTF-8 from str.encode, so a
// multibyte character (never in the ASCII accept sets) collapses to a
// single '_' — not one per byte.
template <typename OkFn>
void sanitize_utf8_append(std::string* out, std::string_view s, OkFn ok) {
  for (size_t i = 0; i < s.size();) {
    unsigned char c = static_cast<unsigned char>(s[i]);
    if (c < 0x80) {
      out->push_back(ok(c) ? static_cast<char>(c) : '_');
      ++i;
    } else {
      out->push_back('_');
      ++i;
      while (i < s.size() &&
             (static_cast<unsigned char>(s[i]) & 0xC0) == 0x80)
        ++i;
    }
  }
}

void prom_append(std::string* out, std::string_view s, bool name_rules) {
  if (name_rules)
    sanitize_utf8_append(out, s, prom_name_ok);
  else
    sanitize_utf8_append(out, s, prom_tag_ok);
}

// One pass emitting "name:value|kind|#tag,..." lines for every masked
// (family, row); sanitize=true applies the exporter character rules,
// sanitize=false forwards names/tags verbatim (DogStatsD re-ingest).
long long emit_statsd_lines(
    std::string* outbuf, const char* meta, long long meta_len,
    long long nrows, const char* suffixes_blob, long long suffixes_len,
    const signed char* family_types, int nfam, const double* values,
    const unsigned char* masks, const char* excl_keys_blob,
    long long excl_keys_len, bool sanitize) {
  std::string& buf = *outbuf;
  buf.clear();
  buf.reserve(static_cast<size_t>(nrows) * nfam * 48);

  std::vector<std::string_view> suffixes =
      split_us(std::string_view(suffixes_blob,
                                static_cast<size_t>(suffixes_len)));
  while (static_cast<int>(suffixes.size()) < nfam)
    suffixes.push_back(std::string_view());
  std::vector<std::string_view> excl_keys = split_us(
      std::string_view(excl_keys_blob, static_cast<size_t>(excl_keys_len)));

  std::vector<std::string_view> recs = split_rs(
      std::string_view(meta, static_cast<size_t>(meta_len)), nrows);

  long long emitted = 0;
  for (int f = 0; f < nfam; ++f) {
    std::string_view suffix = suffixes[f];
    const char kind = family_types[f] == 0 ? 'c' : 'g';
    const double* vals = values + static_cast<size_t>(f) * nrows;
    const unsigned char* mask = masks + static_cast<size_t>(f) * nrows;
    for (long long r = 0; r < nrows; ++r) {
      if (!mask[r]) continue;
      std::string_view rec = recs[static_cast<size_t>(r)];
      size_t nend = rec.find('\x1f');
      std::string_view name =
          nend == std::string_view::npos ? rec : rec.substr(0, nend);
      if (sanitize) {
        prom_append(&buf, name, true);
        prom_append(&buf, suffix, true);
      } else {
        buf.append(name);
        buf.append(suffix);
      }
      buf.push_back(':');
      py_float_append(&buf, vals[r]);
      buf.push_back('|');
      buf.push_back(kind);
      bool first_tag = true;
      if (nend != std::string_view::npos) {
        std::string_view rest = rec.substr(nend + 1);
        for (;;) {
          size_t e = rest.find('\x1f');
          std::string_view tag =
              e == std::string_view::npos ? rest : rest.substr(0, e);
          bool skip = false;
          size_t colon = tag.find(':');
          std::string_view key =
              colon == std::string_view::npos ? tag : tag.substr(0, colon);
          for (std::string_view k : excl_keys) {
            if (key == k) {
              skip = true;
              break;
            }
          }
          if (!skip) {
            buf.append(first_tag ? "|#" : ",");
            if (sanitize)
              prom_append(&buf, tag, false);
            else
              buf.append(tag);
            first_tag = false;
          }
          if (e == std::string_view::npos) break;
          rest = rest.substr(e + 1);
        }
      }
      buf.push_back('\n');
      ++emitted;
    }
  }
  if (!buf.empty()) buf.pop_back();  // no trailing newline
  return emitted;
}

}  // namespace

extern "C" {

// Emits newline-separated statsd lines into a thread-local buffer.
// family_types: 0 counter ("|c"), 1 gauge ("|g"). excl_keys: \x1f-joined
// exact tag keys to drop (server-level exclusion). Returns the emitted
// line count; *out/*out_len carry the buffer.
long long vn_encode_prometheus_lines(
    const char* meta, long long meta_len, long long nrows,
    const char* suffixes_blob, long long suffixes_len,
    const signed char* family_types, int nfam, const double* values,
    const unsigned char* masks, const char* excl_keys_blob,
    long long excl_keys_len, const char** out, long long* out_len) {
  thread_local std::string buf;
  long long n = emit_statsd_lines(
      &buf, meta, meta_len, nrows, suffixes_blob, suffixes_len,
      family_types, nfam, values, masks, excl_keys_blob, excl_keys_len,
      /*sanitize=*/true);
  *out = buf.data();
  *out_len = static_cast<long long>(buf.size());
  return n;
}

// Verbatim DogStatsD forward lines (no sanitization): what a downstream
// statsd/veneur re-ingests. Same contract as
// vn_encode_prometheus_lines otherwise.
long long vn_encode_forward_lines(
    const char* meta, long long meta_len, long long nrows,
    const char* suffixes_blob, long long suffixes_len,
    const signed char* family_types, int nfam, const double* values,
    const unsigned char* masks, const char* excl_keys_blob,
    long long excl_keys_len, const char** out, long long* out_len) {
  thread_local std::string buf;
  long long n = emit_statsd_lines(
      &buf, meta, meta_len, nrows, suffixes_blob, suffixes_len,
      family_types, nfam, values, masks, excl_keys_blob, excl_keys_len,
      /*sanitize=*/false);
  *out = buf.data();
  *out_len = static_cast<long long>(buf.size());
  return n;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Prometheus exposition text: `name{label="value",...} value\n` samples
// (the pushgateway body). Name keeps [a-zA-Z0-9_:], label keys keep
// [a-zA-Z0-9_] (both '_'-substituted), label values are escaped per the
// format (\\, \", \n). "k:v" tags become labels; duplicate sanitized
// keys collapse last-wins at the first occurrence's position (what a
// Python dict does).

namespace {

inline bool expo_name_ok(unsigned char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '_' || c == ':';
}

inline bool expo_label_ok(unsigned char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '_';
}

void expo_label_value_append(std::string* out, std::string_view s) {
  for (char c : s) {
    if (c == '\\')
      out->append("\\\\");
    else if (c == '"')
      out->append("\\\"");
    else if (c == '\n')
      out->append("\\n");
    else
      out->push_back(c);
  }
}

}  // namespace

extern "C" {

// Same argument contract as vn_encode_prometheus_lines; family kinds do
// not appear in the output (exposition samples are untyped without
// TYPE comment lines, which a pushgateway body omits).
long long vn_encode_prometheus_exposition(
    const char* meta, long long meta_len, long long nrows,
    const char* suffixes_blob, long long suffixes_len,
    const signed char* family_types, int nfam, const double* values,
    const unsigned char* masks, const char* excl_keys_blob,
    long long excl_keys_len, const char** out, long long* out_len) {
  (void)family_types;
  thread_local std::string buf;
  buf.clear();
  buf.reserve(static_cast<size_t>(nrows) * nfam * 64);

  std::vector<std::string_view> suffixes =
      split_us(std::string_view(suffixes_blob,
                                static_cast<size_t>(suffixes_len)));
  while (static_cast<int>(suffixes.size()) < nfam)
    suffixes.push_back(std::string_view());
  std::vector<std::string_view> excl_keys = split_us(
      std::string_view(excl_keys_blob, static_cast<size_t>(excl_keys_len)));

  std::vector<std::string_view> recs = split_rs(
      std::string_view(meta, static_cast<size_t>(meta_len)), nrows);

  long long emitted = 0;
  std::vector<std::pair<std::string, std::string_view>> labels;
  for (int f = 0; f < nfam; ++f) {
    std::string_view suffix = suffixes[f];
    const double* vals = values + static_cast<size_t>(f) * nrows;
    const unsigned char* mask = masks + static_cast<size_t>(f) * nrows;
    for (long long r = 0; r < nrows; ++r) {
      if (!mask[r]) continue;
      std::string_view rec = recs[static_cast<size_t>(r)];
      size_t nend = rec.find('\x1f');
      std::string_view name =
          nend == std::string_view::npos ? rec : rec.substr(0, nend);
      labels.clear();
      if (nend != std::string_view::npos) {
        std::string_view rest = rec.substr(nend + 1);
        for (;;) {
          size_t e = rest.find('\x1f');
          std::string_view tag =
              e == std::string_view::npos ? rest : rest.substr(0, e);
          size_t colon = tag.find(':');
          std::string_view rawkey =
              colon == std::string_view::npos ? tag : tag.substr(0, colon);
          std::string_view val =
              colon == std::string_view::npos ? std::string_view()
                                              : tag.substr(colon + 1);
          bool skip = false;
          for (std::string_view k : excl_keys) {
            if (rawkey == k) {
              skip = true;
              break;
            }
          }
          if (!skip) {
            std::string key;
            key.reserve(rawkey.size());
            sanitize_utf8_append(&key, rawkey, expo_label_ok);
            bool replaced = false;
            for (auto& kv : labels) {
              if (kv.first == key) {
                kv.second = val;
                replaced = true;
                break;
              }
            }
            if (!replaced) labels.emplace_back(std::move(key), val);
          }
          if (e == std::string_view::npos) break;
          rest = rest.substr(e + 1);
        }
      }
      sanitize_utf8_append(&buf, name, expo_name_ok);
      sanitize_utf8_append(&buf, suffix, expo_name_ok);
      if (!labels.empty()) {
        buf.push_back('{');
        bool first = true;
        for (auto& kv : labels) {
          if (!first) buf.push_back(',');
          first = false;
          buf.append(kv.first);
          buf.append("=\"");
          expo_label_value_append(&buf, kv.second);
          buf.push_back('"');
        }
        buf.push_back('}');
      }
      buf.push_back(' ');
      expo_value_append(&buf, vals[r]);
      buf.push_back('\n');
      ++emitted;
    }
  }
  *out = buf.data();
  *out_len = static_cast<long long>(buf.size());
  return emitted;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// SignalFx datapoint-body emitter: {"counter":[...],"gauge":[...]}
// from the columnar arrays + meta blob. Dimensions are a JSON object
// built from "k:v" tags (last duplicate key wins, as a Python dict
// does); the hostname dimension key is configurable. Tag-prefix drops
// reject the whole metric (sinks/signalfx.py _convert_fields). The
// single-API-key case only — vary_key_by routing stays in Python.

extern "C" {

// Emits ONE body. family_types: 0 counter, 1 gauge. Returns emitted
// count; -1 on malformed meta.
long long vn_encode_signalfx_body(
    const char* meta, long long meta_len, long long nrows,
    const char* suffixes_blob, long long suffixes_len,
    const signed char* family_types, int nfam, const double* values,
    const unsigned char* masks, long long ts_ms,
    const char* hostname_tag, long long hostname_tag_len,
    const char* hostname, long long hostname_len,
    const char* name_drop_blob, long long name_drop_len,
    const char* tag_drop_blob, long long tag_drop_len,
    const char* excl_keys_blob, long long excl_keys_len,
    const char** out, long long* out_len) {
  thread_local std::string buf;
  thread_local std::string counters_part;
  thread_local std::string gauges_part;
  buf.clear();
  counters_part.clear();
  gauges_part.clear();

  std::vector<std::string_view> suffixes =
      split_us(std::string_view(suffixes_blob,
                                static_cast<size_t>(suffixes_len)));
  while (static_cast<int>(suffixes.size()) < nfam)
    suffixes.push_back(std::string_view());
  std::vector<std::string_view> name_drops = split_us(
      std::string_view(name_drop_blob, static_cast<size_t>(name_drop_len)));
  std::vector<std::string_view> tag_drops = split_us(
      std::string_view(tag_drop_blob, static_cast<size_t>(tag_drop_len)));
  std::vector<std::string_view> excl_keys = split_us(
      std::string_view(excl_keys_blob, static_cast<size_t>(excl_keys_len)));
  std::string_view host_tag(hostname_tag,
                            static_cast<size_t>(hostname_tag_len));
  std::string_view host_val(hostname, static_cast<size_t>(hostname_len));

  std::vector<std::string_view> recs = split_rs(
      std::string_view(meta, static_cast<size_t>(meta_len)), nrows);

  char tsbuf[24];
  std::snprintf(tsbuf, sizeof tsbuf, "%lld", ts_ms);
  long long emitted = 0;
  std::vector<std::pair<std::string_view, std::string_view>> dims;
  for (int f = 0; f < nfam; ++f) {
    std::string_view suffix = suffixes[f];
    std::string& part = family_types[f] == 0 ? counters_part : gauges_part;
    const double* vals = values + static_cast<size_t>(f) * nrows;
    const unsigned char* mask = masks + static_cast<size_t>(f) * nrows;
    for (long long r = 0; r < nrows; ++r) {
      if (!mask[r]) continue;
      std::string_view rec = recs[static_cast<size_t>(r)];
      size_t nend = rec.find('\x1f');
      std::string_view name =
          nend == std::string_view::npos ? rec : rec.substr(0, nend);
      bool dropped = false;
      for (std::string_view p : name_drops) {
        if (name.size() >= p.size() &&
            name.compare(0, p.size(), p) == 0) {
          dropped = true;
          break;
        }
        if (p.size() > name.size()) {
          std::string full(name);
          full.append(suffix);
          if (full.compare(0, p.size(), p) == 0) {
            dropped = true;
            break;
          }
        }
      }
      if (dropped) continue;

      // dimensions: k:v tags, last duplicate key wins (python dict)
      dims.clear();
      if (nend != std::string_view::npos) {
        std::string_view rest = rec.substr(nend + 1);
        for (;;) {
          size_t e = rest.find('\x1f');
          std::string_view tag =
              e == std::string_view::npos ? rest : rest.substr(0, e);
          for (std::string_view p : tag_drops) {
            if (tag.size() >= p.size() &&
                tag.compare(0, p.size(), p) == 0) {
              dropped = true;
              break;
            }
          }
          if (dropped) break;
          size_t colon = tag.find(':');
          std::string_view key =
              colon == std::string_view::npos ? tag : tag.substr(0, colon);
          std::string_view val =
              colon == std::string_view::npos ? std::string_view()
                                              : tag.substr(colon + 1);
          bool excl = false;
          for (std::string_view k : excl_keys) {
            if (key == k) {
              excl = true;
              break;
            }
          }
          if (!excl) {
            bool replaced = false;
            for (auto& kv : dims) {
              if (kv.first == key) {
                kv.second = val;
                replaced = true;
                break;
              }
            }
            if (!replaced) dims.emplace_back(key, val);
          }
          if (e == std::string_view::npos) break;
          rest = rest.substr(e + 1);
        }
      }
      if (dropped) continue;

      if (!part.empty()) part.push_back(',');
      part.append("{\"metric\":\"");
      json_escape_append(&part, name);
      json_escape_append(&part, suffix);
      part.append("\",\"value\":");
      json_number_append(&part, vals[r]);
      part.append(",\"timestamp\":");
      part.append(tsbuf);
      part.append(",\"dimensions\":{");
      // a tag with the hostname key overrides the default host dim
      // (python seeds dims with it, then tags overwrite)
      bool host_overridden = false;
      for (auto& kv : dims) {
        if (kv.first == host_tag) {
          host_overridden = true;
          break;
        }
      }
      bool first_dim = true;
      if (!host_overridden) {
        part.push_back('"');
        json_escape_append(&part, host_tag);
        part.append("\":\"");
        json_escape_append(&part, host_val);
        part.push_back('"');
        first_dim = false;
      }
      for (auto& kv : dims) {
        if (!first_dim) part.push_back(',');
        first_dim = false;
        part.push_back('"');
        json_escape_append(&part, kv.first);
        part.append("\":\"");
        json_escape_append(&part, kv.second);
        part.push_back('"');
      }
      part.append("}}");
      ++emitted;
    }
  }
  buf.push_back('{');
  bool any = false;
  if (!counters_part.empty()) {
    buf.append("\"counter\":[");
    buf.append(counters_part);
    buf.push_back(']');
    any = true;
  }
  if (!gauges_part.empty()) {
    if (any) buf.push_back(',');
    buf.append("\"gauge\":[");
    buf.append(gauges_part);
    buf.push_back(']');
  }
  buf.push_back('}');
  *out = buf.data();
  *out_len = static_cast<long long>(buf.size());
  return emitted;
}

// ---------------------------------------------------------------------------
// zlib deflate, pinned byte-identical to Python zlib.compress (both use
// the system zlib at Z_DEFAULT_COMPRESSION with default deflateInit
// parameters, so the streams match bit for bit — the parity test holds
// the pin). Thread-local output; GIL released via ctypes like every
// other emitter, so compressing a 25k-entry body no longer serializes
// against ingest.

long long vn_deflate(const char* buf, long long len, const char** out,
                     long long* out_len) {
  thread_local std::string zbuf;
  uLong bound = compressBound(static_cast<uLong>(len));
  zbuf.resize(bound);
  uLongf dlen = bound;
  if (compress2(reinterpret_cast<Bytef*>(&zbuf[0]), &dlen,
                reinterpret_cast<const Bytef*>(buf),
                static_cast<uLong>(len), Z_DEFAULT_COMPRESSION) != Z_OK)
    return -1;
  *out = zbuf.data();
  *out_len = static_cast<long long>(dlen);
  return static_cast<long long>(dlen);
}

// Deflate n_chunks slices of one buffer (the datadog emitter's chunked
// bodies) in a single GIL-free call: offs is [n_chunks+1] input
// offsets; *out_offs_out gets [n_chunks+1] offsets into the compressed
// output buffer. Returns n_chunks, or -1 on a zlib error. Output
// buffers are distinct from the emitters' (chaining
// vn_encode_datadog_series -> vn_deflate_chunks on one thread is safe).
long long vn_deflate_chunks(const char* buf, const long long* offs,
                            long long n_chunks,
                            const long long** out_offs_out,
                            const char** out, long long* out_len) {
  thread_local std::string zbuf;
  thread_local std::vector<long long> zoffs;
  zbuf.clear();
  zoffs.clear();
  for (long long i = 0; i < n_chunks; ++i) {
    const char* src = buf + offs[i];
    uLong slen = static_cast<uLong>(offs[i + 1] - offs[i]);
    uLong bound = compressBound(slen);
    size_t start = zbuf.size();
    zoffs.push_back(static_cast<long long>(start));
    zbuf.resize(start + bound);
    uLongf dlen = bound;
    if (compress2(reinterpret_cast<Bytef*>(&zbuf[start]), &dlen,
                  reinterpret_cast<const Bytef*>(src), slen,
                  Z_DEFAULT_COMPRESSION) != Z_OK)
      return -1;
    zbuf.resize(start + dlen);
  }
  zoffs.push_back(static_cast<long long>(zbuf.size()));
  *out_offs_out = zoffs.data();
  *out = zbuf.data();
  *out_len = static_cast<long long>(zbuf.size());
  return n_chunks;
}

// ---------------------------------------------------------------------------
// VMB1 archive section (veneur_tpu/archive/wire.py SECTION_COLUMNAR):
// one ColumnGroup serialized dense — a first-appearance local string
// table (per-row name then tags, then family suffixes), the row
// metadata table, the family table, then the f64 value / u8 mask planes
// memcpy'd straight from the flush arrays. Byte-identical to the Python
// encoder (_columnar_section_py), pinned by tests/test_archive.py; all
// integers little-endian (LE-only CI, like the span wire). Returns the
// emitted sample count (mask popcount), or -1 on malformed meta.

long long vn_encode_archive_section(
    const char* meta, long long meta_len, long long nrows,
    const char* suffixes_blob, long long suffixes_len,
    const signed char* family_types, int nfam, const double* values,
    const unsigned char* masks, const char** out, long long* out_len) {
  thread_local std::string buf;
  buf.clear();

  auto put_u16 = [](std::string* b, unsigned v) {
    char t[2] = {static_cast<char>(v), static_cast<char>(v >> 8)};
    b->append(t, 2);
  };
  auto put_u32 = [](std::string* b, unsigned long v) {
    char t[4] = {static_cast<char>(v), static_cast<char>(v >> 8),
                 static_cast<char>(v >> 16), static_cast<char>(v >> 24)};
    b->append(t, 4);
  };

  std::vector<std::string_view> strings;
  std::unordered_map<std::string_view, uint32_t> ids;
  auto sid = [&](std::string_view s) -> uint32_t {
    auto it = ids.find(s);
    if (it != ids.end()) return it->second;
    uint32_t i = static_cast<uint32_t>(strings.size());
    ids.emplace(s, i);
    strings.push_back(s);
    return i;
  };

  std::vector<std::string_view> recs = split_rs(
      std::string_view(meta, static_cast<size_t>(meta_len)), nrows);
  std::string rows;
  rows.reserve(static_cast<size_t>(nrows) * 10);
  for (auto& rec : recs) {
    std::vector<std::string_view> fields = split_us(rec);
    if (fields.empty()) fields.push_back(std::string_view());
    if (fields.size() - 1 > 0xFFFF) return -1;
    put_u32(&rows, sid(fields[0]));
    put_u16(&rows, static_cast<unsigned>(fields.size() - 1));
    for (size_t t = 1; t < fields.size(); ++t) put_u32(&rows, sid(fields[t]));
  }

  std::vector<std::string_view> suffixes =
      split_us(std::string_view(suffixes_blob,
                                static_cast<size_t>(suffixes_len)));
  while (static_cast<int>(suffixes.size()) < nfam)
    suffixes.push_back(std::string_view());
  std::string fams;
  put_u32(&fams, static_cast<unsigned long>(nfam));
  for (int f = 0; f < nfam; ++f) {
    fams.push_back(static_cast<char>(family_types[f]));
    put_u32(&fams, sid(suffixes[static_cast<size_t>(f)]));
  }

  size_t plane = static_cast<size_t>(nfam) * static_cast<size_t>(nrows);
  buf.reserve(rows.size() + fams.size() + plane * 9 + strings.size() * 12);
  put_u32(&buf, static_cast<unsigned long>(strings.size()));
  for (auto& s : strings) {
    put_u32(&buf, static_cast<unsigned long>(s.size()));
    buf.append(s.data(), s.size());
  }
  put_u32(&buf, static_cast<unsigned long>(nrows));
  buf.append(rows);
  buf.append(fams);
  buf.append(reinterpret_cast<const char*>(values), plane * 8);
  buf.append(reinterpret_cast<const char*>(masks), plane);

  long long count = 0;
  for (size_t i = 0; i < plane; ++i) count += masks[i] ? 1 : 0;
  *out = buf.data();
  *out_len = static_cast<long long>(buf.size());
  return count;
}

}  // extern "C"
