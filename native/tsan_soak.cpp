// ThreadSanitizer soak for the native ingest/commit path.
//
// The Go reference's race-correctness strategy is running its whole test
// suite under `go test -race` (reference .circleci/config.yml:104-112).
// This driver is the equivalent gate for OUR native hot path: it links
// dogstatsd.cpp directly, spins up the same thread topology the Python
// runtime creates (multiple UDP readers calling vn_ingest_routed over
// shared shard contexts, SSF span readers on one shared span context, a
// flush thread draining every context, a telemetry thread reading the
// stats counters, an import thread upserting series), and runs them all
// concurrently under -fsanitize=thread. Any data race on the shard
// mutex discipline aborts the build (TSan exits non-zero).
//
// Built+run by `make -C native tsan` (tools/ci.sh runs it).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* vn_ctx_new(int hll_precision);
void vn_ctx_free(void* p);
int vn_ingest(void* p, const char* buf, int len);
int vn_ingest_routed(void** ctxps, int nctx, const char* buf, int len);
int vn_ingest_ssf_many(void* p, const char* buf, long long len,
                       const char* ind_name, int ind_len, const char* obj_name,
                       int obj_len, double uniq_rate, int* errors_out,
                       int* fallback_off, int* fallback_len, int fallback_cap,
                       int* nfall_out);
int vn_drain_histo(void* p, int32_t* rows, float* vals, float* wts, int cap);
int vn_drain_set(void* p, int32_t* rows, int32_t* idx, int8_t* rank, int cap);
int vn_drain_counter(void* p, int32_t* rows, double* contribs, int cap);
int vn_drain_gauge(void* p, int32_t* rows, double* vals, int cap);
int vn_drain_new_series(void* p, int32_t* pools, int32_t* rows,
                        int32_t* kinds, int32_t* scopes, char* strbuf,
                        int strcap, int* strlen_out, int max);
int vn_drain_ssf_services(void* p, char* buf, int cap);
int vn_drain_other(void* p, char* buf, int cap);
int vn_upsert(void* p, const char* name, int name_len, int kind,
              const char* joined_tags, int tags_len, int scope_class);
long long vn_processed(void* p);
long long vn_errors(void* p);
int vn_pending_histo(void* p);
int vn_pending_set(void* p);
int vn_pending_counter(void* p);
int vn_pending_gauge(void* p);
void vn_set_lock_stats(int enabled);
int vn_lock_stats(void* p, long long out[5], long long* wait_out,
                  long long* hold_out);
void vn_set_stage_depth(void* p, int depth);
void* vn_stage_detach(void* p, float** vals, float** wts, int32_t** counts,
                      int32_t* rows_out, int32_t* depth_out);
void vn_stage_free(void* plane);
long long vn_stage_total(void* p);
}

namespace {

constexpr int kShards = 4;
constexpr int kReaders = 4;
constexpr int kPacketsPerReader = 39996;  // divisible by the 6-case rotation
constexpr int kSsfThreads = 2;
constexpr int kSsfBatches = 200;
constexpr int kSpansPerBatch = 64;

std::atomic<bool> done{false};
std::atomic<long long> sent_ok{0}, sent_bad{0}, sent_evt{0};

void put_varint(std::string* out, uint64_t v) {
  while (v >= 0x80) {
    out->push_back(static_cast<char>((v & 0x7f) | 0x80));
    v >>= 7;
  }
  out->push_back(static_cast<char>(v));
}

// Minimal wire-format SSFSpan (proto/ssf.proto fields: trace_id=2 id=3
// start=5 end=6 service=8 indicator=12 name=13), framed [u32 LE len].
std::string make_ssf_batch(int seed) {
  std::string out;
  for (int i = 0; i < kSpansPerBatch; ++i) {
    std::string span;
    put_varint(&span, (2 << 3) | 0);  // trace_id
    put_varint(&span, 1000 + seed);
    put_varint(&span, (3 << 3) | 0);  // id
    put_varint(&span, 1 + i);
    put_varint(&span, (5 << 3) | 0);  // start_timestamp
    put_varint(&span, 1700000000000000000ull + i);
    put_varint(&span, (6 << 3) | 0);  // end_timestamp
    put_varint(&span, 1700000000000000000ull + i + 5000000);
    const char* svc = (i % 2) ? "svc-a" : "svc-b";
    put_varint(&span, (8 << 3) | 2);  // service
    put_varint(&span, std::strlen(svc));
    span += svc;
    put_varint(&span, (12 << 3) | 0);  // indicator
    put_varint(&span, 1);
    put_varint(&span, (13 << 3) | 2);  // name
    put_varint(&span, 2);
    span += "op";
    uint32_t len = static_cast<uint32_t>(span.size());
    char hdr[4];
    std::memcpy(hdr, &len, 4);
    out.append(hdr, 4);
    out += span;
  }
  return out;
}

void reader_thread(std::vector<void*>* ctxs, int tid) {
  char line[128];
  for (int i = 0; i < kPacketsPerReader; ++i) {
    int n;
    int kind = i % 6;
    switch (kind) {
      case 0:
        n = std::snprintf(line, sizeof line, "soak.timer%d:%d|ms|#t:%d",
                          i % 64, i % 1000, tid);
        break;
      case 1:
        n = std::snprintf(line, sizeof line, "soak.count:%d|c|@0.5", i % 7);
        break;
      case 2:
        n = std::snprintf(line, sizeof line, "soak.gauge%d:%d|g", tid, i);
        break;
      case 3:
        n = std::snprintf(line, sizeof line, "soak.set:user%d|s", i % 997);
        break;
      case 4:  // malformed: exercises the error path under contention
        n = std::snprintf(line, sizeof line, "soak.bad:%d|q", i);
        break;
      default:  // event: races the other_lines append in vn_ingest_routed
                // against the drain thread's vn_drain_other boundary cut
        n = std::snprintf(line, sizeof line, "_e{9,2}:soaktitle|hi|#t:%d",
                          tid);
        break;
    }
    int rc = vn_ingest_routed(ctxs->data(), kShards, line, n);
    if (kind == 5)
      sent_evt.fetch_add(1, std::memory_order_relaxed);
    else if (rc > 0)
      sent_ok.fetch_add(rc, std::memory_order_relaxed);
    else
      sent_bad.fetch_add(1, std::memory_order_relaxed);
  }
}

void ssf_thread(void* ctx, int tid) {
  std::string batch = make_ssf_batch(tid);
  for (int i = 0; i < kSsfBatches; ++i) {
    int errs = 0, nfall = 0;
    vn_ingest_ssf_many(ctx, batch.data(),
                       static_cast<long long>(batch.size()), "ind", 3, "obj",
                       3, 0.0, &errs, nullptr, nullptr, 0, &nfall);
  }
}

// The flush loop: drain every pool of every context while readers are
// still committing — the exact overlap the two-phase flush runs.
void drain_thread(std::vector<void*>* all_ctxs) {
  constexpr int kCap = 8192;
  std::vector<int32_t> rows(kCap), idx(kCap), pools(kCap), kinds(kCap),
      scopes(kCap);
  std::vector<float> vals(kCap), wts(kCap);
  std::vector<double> dvals(kCap);
  std::vector<int8_t> rank(kCap);
  std::vector<char> namebuf(kCap * 64);
  int stroff = 0;
  long long detaches = 0;
  while (!done.load(std::memory_order_acquire)) {
    for (void* c : *all_ctxs) {
      // periodic staged-plane detach races the readers' staging stores
      // (the per-flush handoff under the ctx mutex)
      float *sv, *sw;
      int32_t* scnt;
      int32_t srows, sdepth;
      void* plane = vn_stage_detach(c, &sv, &sw, &scnt, &srows, &sdepth);
      if (plane != nullptr) {
        // read the handed-off memory like the uploader does
        volatile float probe = sv[0] + sw[0] + (float)scnt[0];
        (void)probe;
        ++detaches;
        vn_stage_free(plane);
      }
      vn_drain_histo(c, rows.data(), vals.data(), wts.data(), kCap);
      vn_drain_set(c, rows.data(), idx.data(), rank.data(), kCap);
      vn_drain_counter(c, rows.data(), dvals.data(), kCap);
      vn_drain_gauge(c, rows.data(), dvals.data(), kCap);
      vn_drain_new_series(c, pools.data(), rows.data(), kinds.data(),
                          scopes.data(), namebuf.data(),
                          static_cast<int>(namebuf.size()), &stroff, kCap);
      vn_drain_ssf_services(c, namebuf.data(),
                            static_cast<int>(namebuf.size()));
      vn_drain_other(c, namebuf.data(), static_cast<int>(namebuf.size()));
    }
  }
}

// Self-telemetry: reads the counters the scopedstatsd reporter polls.
void stats_thread(std::vector<void*>* all_ctxs) {
  long long out[5], wait = 0, hold = 0;
  while (!done.load(std::memory_order_acquire)) {
    for (void* c : *all_ctxs) {
      (void)vn_processed(c);
      (void)vn_errors(c);
      (void)vn_pending_histo(c);
      (void)vn_pending_set(c);
      (void)vn_pending_counter(c);
      (void)vn_pending_gauge(c);
      (void)vn_lock_stats(c, out, &wait, &hold);
    }
  }
}

// The import path: registers series directly, racing the parser's own
// directory upserts on the same contexts.
void upsert_thread(std::vector<void*>* ctxs) {
  char name[64];
  for (int i = 0; i < 20000; ++i) {
    int n = std::snprintf(name, sizeof name, "import.series%d", i % 512);
    vn_upsert((*ctxs)[i % kShards], name, n, i % 4, "env:prod", 8, 0);
  }
}

}  // namespace

int main() {
  vn_set_lock_stats(1);
  std::vector<void*> shard_ctxs;
  for (int i = 0; i < kShards; ++i) {
    void* c = vn_ctx_new(12);
    // small depth so both the staging store AND the full-row spill path
    // run under the sanitizer
    vn_set_stage_depth(c, 8);
    shard_ctxs.push_back(c);
  }
  void* ssf_ctx = vn_ctx_new(12);
  std::vector<void*> all_ctxs = shard_ctxs;
  all_ctxs.push_back(ssf_ctx);

  std::vector<std::thread> threads;
  threads.emplace_back(drain_thread, &all_ctxs);
  threads.emplace_back(stats_thread, &all_ctxs);
  threads.emplace_back(upsert_thread, &shard_ctxs);
  for (int t = 0; t < kReaders; ++t)
    threads.emplace_back(reader_thread, &shard_ctxs, t);
  for (int t = 0; t < kSsfThreads; ++t)
    threads.emplace_back(ssf_thread, ssf_ctx, t);

  for (size_t i = 2; i < threads.size(); ++i) threads[i].join();
  done.store(true, std::memory_order_release);
  threads[0].join();
  threads[1].join();

  // conservation: every accepted datagram was counted exactly once
  long long processed = 0, errors = 0;
  for (void* c : shard_ctxs) {
    processed += vn_processed(c);
    errors += vn_errors(c);
  }
  long long want_ok = sent_ok.load(), want_bad = sent_bad.load();
  long long want_bad_expect = (long long)kReaders * (kPacketsPerReader / 6);
  std::printf("tsan_soak: processed=%lld errors=%lld sent_ok=%lld "
              "sent_bad=%lld events=%lld\n",
              processed, errors, want_ok, want_bad, sent_evt.load());
  bool ok = processed == want_ok && errors == want_bad &&
            want_bad == want_bad_expect;
  for (void* c : all_ctxs) vn_ctx_free(c);
  if (!ok) {
    std::fprintf(stderr, "tsan_soak: conservation FAILED\n");
    return 1;
  }
  std::puts("tsan_soak: OK");
  return 0;
}
