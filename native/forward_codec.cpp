// Native VSF1/VDE1 forward-frame codec (third TU of libveneur_native.so).
//
// The streaming forward hop frames every payload twice: a VDE1 dedup
// envelope header (canonical one-line JSON, distributed/codec.py
// encode_dedup_envelope) and a VSF1 stream frame (magic + u64 LE seq).
// Both run per-frame on the proxy fan-out, so like the emit tier
// (emit.cpp) they move here and run with the GIL released; the Python
// reference implementations stay pinned byte-identical and every entry
// point returns a "fall back" code for any input whose Python semantics
// this TU does not replicate exactly (non-UTF-8 senders, out-of-i64
// ints, non-canonical headers), so the wrappers never change behavior —
// only speed.
//
// Out-buffer contract matches emit.cpp: results live in thread_local
// std::string buffers, valid until the calling thread's next call.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

namespace {

thread_local std::string g_frame_buf;
thread_local std::string g_hdr_buf;
thread_local std::string g_sender_buf;

const char kFrameMagic[4] = {'V', 'S', 'F', '1'};
const char kDedupMagic[4] = {'V', 'D', 'E', '1'};

void put_u64_le(std::string& out, uint64_t v) {
    char b[8];
    for (int i = 0; i < 8; i++) b[i] = (char)((v >> (8 * i)) & 0xff);
    out.append(b, 8);
}

uint64_t get_u64_le(const unsigned char* p) {
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v |= (uint64_t)p[i] << (8 * i);
    return v;
}

// json.dumps ensure_ascii string escape: \" \\ \b \t \n \f \r, \u00xx
// for remaining chars outside 0x20..0x7e, and \uxxxx (surrogate pairs
// for astral planes, lowercase hex) for non-ASCII code points decoded
// from the UTF-8 input. Returns false on malformed UTF-8 (overlong,
// truncated, surrogate, out of range) — caller falls back to Python.
bool json_escape_utf8(const unsigned char* s, long long n,
                      std::string& out) {
    char tmp[16];
    long long i = 0;
    while (i < n) {
        unsigned char c = s[i];
        if (c == '"') { out += "\\\""; i++; }
        else if (c == '\\') { out += "\\\\"; i++; }
        else if (c == '\b') { out += "\\b"; i++; }
        else if (c == '\t') { out += "\\t"; i++; }
        else if (c == '\n') { out += "\\n"; i++; }
        else if (c == '\f') { out += "\\f"; i++; }
        else if (c == '\r') { out += "\\r"; i++; }
        else if (c < 0x20 || c == 0x7f) {
            snprintf(tmp, sizeof tmp, "\\u%04x", c);
            out += tmp;
            i++;
        } else if (c < 0x80) {
            out += (char)c;
            i++;
        } else {
            unsigned cp;
            int len;
            if ((c & 0xe0) == 0xc0) { len = 2; cp = c & 0x1f; }
            else if ((c & 0xf0) == 0xe0) { len = 3; cp = c & 0x0f; }
            else if ((c & 0xf8) == 0xf0) { len = 4; cp = c & 0x07; }
            else return false;
            if (i + len > n) return false;
            for (int k = 1; k < len; k++) {
                unsigned char cc = s[i + k];
                if ((cc & 0xc0) != 0x80) return false;
                cp = (cp << 6) | (cc & 0x3f);
            }
            if (cp > 0x10ffff) return false;
            if (cp >= 0xd800 && cp <= 0xdfff) return false;
            if ((len == 2 && cp < 0x80) || (len == 3 && cp < 0x800) ||
                (len == 4 && cp < 0x10000))
                return false;  // overlong
            if (cp < 0x10000) {
                snprintf(tmp, sizeof tmp, "\\u%04x", cp);
                out += tmp;
            } else {
                cp -= 0x10000;
                snprintf(tmp, sizeof tmp, "\\u%04x\\u%04x",
                         0xd800 + (cp >> 10), 0xdc00 + (cp & 0x3ff));
                out += tmp;
            }
            i += len;
        }
    }
    return true;
}

void utf8_append(std::string& out, unsigned cp) {
    if (cp < 0x80) {
        out += (char)cp;
    } else if (cp < 0x800) {
        out += (char)(0xc0 | (cp >> 6));
        out += (char)(0x80 | (cp & 0x3f));
    } else if (cp < 0x10000) {
        out += (char)(0xe0 | (cp >> 12));
        out += (char)(0x80 | ((cp >> 6) & 0x3f));
        out += (char)(0x80 | (cp & 0x3f));
    } else {
        out += (char)(0xf0 | (cp >> 18));
        out += (char)(0x80 | ((cp >> 12) & 0x3f));
        out += (char)(0x80 | ((cp >> 6) & 0x3f));
        out += (char)(0x80 | (cp & 0x3f));
    }
}

int hex_val(unsigned char c) {
    if (c >= '0' && c <= '9') return c - '0';
    if (c >= 'a' && c <= 'f') return c - 'a' + 10;
    if (c >= 'A' && c <= 'F') return c - 'A' + 10;
    return -1;
}

// Strict JSON string body (between the quotes) -> UTF-8 in `out`.
// Only ASCII input is accepted (the canonical encoder is ensure_ascii);
// lone surrogates fall back — json.loads accepts them but the result
// can't travel through a UTF-8 out-buffer. Advances *pos past the
// closing quote. Returns false -> caller falls back to Python.
bool parse_json_string(const unsigned char* h, long long n,
                       long long* pos, std::string& out) {
    long long i = *pos;
    while (i < n) {
        unsigned char c = h[i];
        if (c == '"') {
            *pos = i + 1;
            return true;
        }
        if (c < 0x20 || c >= 0x80) return false;  // strict / non-ASCII
        if (c != '\\') {
            out += (char)c;
            i++;
            continue;
        }
        if (i + 1 >= n) return false;
        unsigned char e = h[i + 1];
        i += 2;
        switch (e) {
            case '"': out += '"'; break;
            case '\\': out += '\\'; break;
            case '/': out += '/'; break;
            case 'b': out += '\b'; break;
            case 'f': out += '\f'; break;
            case 'n': out += '\n'; break;
            case 'r': out += '\r'; break;
            case 't': out += '\t'; break;
            case 'u': {
                if (i + 4 > n) return false;
                unsigned cp = 0;
                for (int k = 0; k < 4; k++) {
                    int v = hex_val(h[i + k]);
                    if (v < 0) return false;
                    cp = (cp << 4) | (unsigned)v;
                }
                i += 4;
                if (cp >= 0xdc00 && cp <= 0xdfff) return false;
                if (cp >= 0xd800 && cp <= 0xdbff) {
                    if (i + 6 > n || h[i] != '\\' || h[i + 1] != 'u')
                        return false;
                    unsigned lo = 0;
                    for (int k = 0; k < 4; k++) {
                        int v = hex_val(h[i + 2 + k]);
                        if (v < 0) return false;
                        lo = (lo << 4) | (unsigned)v;
                    }
                    if (lo < 0xdc00 || lo > 0xdfff) return false;
                    i += 6;
                    cp = 0x10000 + ((cp - 0xd800) << 10) + (lo - 0xdc00);
                }
                utf8_append(out, cp);
                break;
            }
            default:
                return false;
        }
    }
    return false;  // unterminated
}

// Decimal integer with i64 overflow detection; no leading zeros beyond
// a bare "0", no sign handling beyond one leading '-' (the canonical
// encoder never emits "+" or exponents). Returns false -> fall back
// (Python ints are unbounded, json.loads parses what we can't).
bool parse_json_int(const unsigned char* h, long long n, long long* pos,
                    long long* out) {
    long long i = *pos;
    bool neg = false;
    if (i < n && h[i] == '-') {
        neg = true;
        i++;
    }
    if (i >= n || h[i] < '0' || h[i] > '9') return false;
    if (h[i] == '0' && i + 1 < n && h[i + 1] >= '0' && h[i + 1] <= '9')
        return false;  // leading zero: not canonical
    uint64_t v = 0;
    const uint64_t lim = neg ? (uint64_t)1 << 63
                             : ((uint64_t)1 << 63) - 1;
    while (i < n && h[i] >= '0' && h[i] <= '9') {
        unsigned d = h[i] - '0';
        if (v > (lim - d) / 10) return false;  // i64 overflow
        v = v * 10 + d;
        i++;
    }
    *pos = i;
    *out = neg ? (long long)(-(int64_t)v) : (long long)v;
    return true;
}

bool expect(const unsigned char* h, long long n, long long* pos,
            const char* lit) {
    size_t len = strlen(lit);
    if (*pos + (long long)len > n) return false;
    if (memcmp(h + *pos, lit, len) != 0) return false;
    *pos += (long long)len;
    return true;
}

}  // namespace

extern "C" {

// ---------------------------------------------------------- VSF1 frame

// Full frame (magic + u64 LE seq + body) into the thread-local buffer.
// Returns 0; *out is valid until this thread's next call.
long long vn_stream_frame_encode(unsigned long long seq,
                                 const unsigned char* body,
                                 long long body_len,
                                 const char** out, long long* out_len) {
    g_frame_buf.clear();
    g_frame_buf.reserve(12 + (size_t)(body_len > 0 ? body_len : 0));
    g_frame_buf.append(kFrameMagic, 4);
    put_u64_le(g_frame_buf, seq);
    if (body_len > 0) g_frame_buf.append((const char*)body,
                                         (size_t)body_len);
    *out = g_frame_buf.data();
    *out_len = (long long)g_frame_buf.size();
    return 0;
}

// Returns the body offset (12) with *seq_out filled, or -1 on a blob
// that is not a VSF1 frame (wrapper raises ValueError, like Python).
long long vn_stream_frame_decode(const unsigned char* blob,
                                 long long len,
                                 unsigned long long* seq_out) {
    if (len < 12 || memcmp(blob, kFrameMagic, 4) != 0) return -1;
    *seq_out = get_u64_le(blob + 4);
    return 12;
}

// ------------------------------------------------------------ VSF1 ack

// 9 ack bytes (u64 LE seq + u8 status) into the caller's buffer.
long long vn_stream_ack_encode(unsigned long long seq, int status,
                               unsigned char* out9) {
    for (int i = 0; i < 8; i++)
        out9[i] = (unsigned char)((seq >> (8 * i)) & 0xff);
    out9[8] = (unsigned char)(status & 0xff);
    return 0;
}

// Returns the status byte (0..255) with *seq_out filled, or -1 when
// the blob is not exactly 9 bytes.
long long vn_stream_ack_decode(const unsigned char* blob, long long len,
                               unsigned long long* seq_out) {
    if (len != 9) return -1;
    *seq_out = get_u64_le(blob);
    return (long long)blob[8];
}

// -------------------------------------------------------- VDE1 envelope

// Envelope prefix (magic + u16 LE header length + canonical JSON
// header) into the thread-local buffer; the wrapper appends the body.
// Returns 0 on success, -1 on malformed-UTF-8 sender (fall back to
// Python), -2 when the header exceeds the u16 length field (wrapper
// raises the pinned "dedup header too large" ValueError).
long long vn_dedup_header_encode(const unsigned char* sender,
                                 long long sender_len,
                                 long long dedup_id, long long count,
                                 const char** out, long long* out_len) {
    g_hdr_buf.clear();
    g_hdr_buf.reserve(32 + (size_t)(sender_len > 0 ? sender_len : 0));
    g_hdr_buf += "{\"s\":\"";
    if (!json_escape_utf8(sender, sender_len, g_hdr_buf)) return -1;
    char tmp[48];
    snprintf(tmp, sizeof tmp, "\",\"i\":%lld,\"n\":%lld}", dedup_id,
             count);
    g_hdr_buf += tmp;
    size_t hlen = g_hdr_buf.size();
    if (hlen > 0xffff) return -2;
    g_frame_buf.clear();
    g_frame_buf.reserve(6 + hlen);
    g_frame_buf.append(kDedupMagic, 4);
    g_frame_buf += (char)(hlen & 0xff);
    g_frame_buf += (char)((hlen >> 8) & 0xff);
    g_frame_buf += g_hdr_buf;
    *out = g_frame_buf.data();
    *out_len = (long long)g_frame_buf.size();
    return 0;
}

// Strict canonical parse of the JSON header bytes (what the canonical
// encoder emits: {"s":<string>,"i":<int>,"n":<int>}, no whitespace, no
// reordering). Returns 0 with sender (UTF-8, thread-local) + id +
// count, or -1 for anything else — the wrapper falls back to
// json.loads so non-canonical-but-valid headers keep their exact
// Python semantics (bigints, float coercion, lone surrogates, ...).
long long vn_dedup_header_parse(const unsigned char* hdr, long long hlen,
                                const char** sender_out,
                                long long* sender_len,
                                long long* id_out,
                                long long* count_out) {
    long long pos = 0;
    if (!expect(hdr, hlen, &pos, "{\"s\":\"")) return -1;
    g_sender_buf.clear();
    if (!parse_json_string(hdr, hlen, &pos, g_sender_buf)) return -1;
    if (!expect(hdr, hlen, &pos, ",\"i\":")) return -1;
    if (!parse_json_int(hdr, hlen, &pos, id_out)) return -1;
    if (!expect(hdr, hlen, &pos, ",\"n\":")) return -1;
    if (!parse_json_int(hdr, hlen, &pos, count_out)) return -1;
    if (!expect(hdr, hlen, &pos, "}")) return -1;
    if (pos != hlen) return -1;
    *sender_out = g_sender_buf.data();
    *sender_len = (long long)g_sender_buf.size();
    return 0;
}

}  // extern "C"
