// Native ingest hot path: DogStatsD parsing, tag normalization, series
// directory, and SoA batch building.
//
// The reference's per-packet CPU hotspot is its zero-allocation Go parser +
// map upsert (samplers/parser.go:298-423, worker.go:108-177, SURVEY.md
// §3.2). Here the whole host-side ingest path is one C++ translation unit:
// a packet buffer goes in; dense (row, value, weight) SoA arrays come out,
// ready to be shipped to the device. Row assignment (the series directory)
// lives in an open-addressing hash table keyed by the same 32-bit FNV-1a
// identity digest the Python parser computes, so both front ends agree.
//
// Events (_e{) and service checks (_sc) are rare control-plane traffic and
// are handed back to Python verbatim.
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).

#include <emmintrin.h>  // SSE2 delimiter masks (MaskFinder)
#include <errno.h>
#include <fcntl.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cctype>
#include <charconv>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// Commit-path lock instrumentation gate (vn_set_lock_stats): off by
// default so the per-line clock reads never tax production ingest.
std::atomic<bool> g_lock_stats{false};

inline int64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

constexpr uint32_t kFnv32Offset = 2166136261u;
constexpr uint32_t kFnv32Prime = 16777619u;
constexpr uint64_t kFnv64Offset = 0xcbf29ce484222325ull;
constexpr uint64_t kFnv64Prime = 0x100000001b3ull;

inline uint32_t fnv1a32(std::string_view s, uint32_t h = kFnv32Offset) {
  for (unsigned char c : s) {
    h ^= c;
    h *= kFnv32Prime;
  }
  return h;
}

inline uint64_t fnv1a64_continue(std::string_view s, uint64_t h) {
  for (unsigned char c : s) {
    h ^= c;
    h *= kFnv64Prime;
  }
  return h;
}

inline uint64_t fnv1a64(std::string_view s) {
  return fnv1a64_continue(s, kFnv64Offset);
}

inline uint64_t fmix64(uint64_t h) {
  h ^= h >> 33;
  h *= 0xff51afd7ed558ccdull;
  h ^= h >> 33;
  h *= 0xc4ceb9fe1a85ec53ull;
  h ^= h >> 33;
  return h;
}

// MetroHash64 — the Go fleet's set-element hash (vendored
// axiomhq/hyperloglog hashes with metro64 seed=1337; see
// utils/hashing.py metro_hash64 for the Python twin and the interop
// rationale). Enabled per-context via vn_ctx_set_metro.
inline uint64_t rotr64(uint64_t v, int k) { return (v >> k) | (v << (64 - k)); }

inline uint64_t load_le(const char* p, int n) {
  uint64_t v = 0;
  std::memcpy(&v, p, n);  // little-endian hosts only (x86/ARM LE)
  return v;
}

uint64_t metro_hash64(std::string_view s, uint64_t seed) {
  constexpr uint64_t k0 = 0xD6D018F5, k1 = 0xA2AA033B, k2 = 0x62992FC1,
                     k3 = 0x30BC5B29;
  const char* p = s.data();
  size_t n = s.size();
  uint64_t h = (seed + k2) * k0;
  if (n >= 32) {
    uint64_t v0 = h, v1 = h, v2 = h, v3 = h;
    while (n >= 32) {
      v0 += load_le(p, 8) * k0; v0 = rotr64(v0, 29) + v2;
      v1 += load_le(p + 8, 8) * k1; v1 = rotr64(v1, 29) + v3;
      v2 += load_le(p + 16, 8) * k2; v2 = rotr64(v2, 29) + v0;
      v3 += load_le(p + 24, 8) * k3; v3 = rotr64(v3, 29) + v1;
      p += 32;
      n -= 32;
    }
    v2 ^= rotr64((v0 + v3) * k0 + v1, 37) * k1;
    v3 ^= rotr64((v1 + v2) * k1 + v0, 37) * k0;
    v0 ^= rotr64((v0 + v2) * k0 + v3, 37) * k1;
    v1 ^= rotr64((v1 + v3) * k1 + v2, 37) * k0;
    h += v0 ^ v1;
  }
  if (n >= 16) {
    uint64_t v0 = h + load_le(p, 8) * k2; v0 = rotr64(v0, 29) * k3;
    uint64_t v1 = h + load_le(p + 8, 8) * k2; v1 = rotr64(v1, 29) * k3;
    v0 ^= rotr64(v0 * k0, 21) + v1;
    v1 ^= rotr64(v1 * k3, 21) + v0;
    h += v1;
    p += 16;
    n -= 16;
  }
  if (n >= 8) {
    h += load_le(p, 8) * k3;
    h ^= rotr64(h, 55) * k1;
    p += 8;
    n -= 8;
  }
  if (n >= 4) {
    h += load_le(p, 4) * k3;
    h ^= rotr64(h, 26) * k1;
    p += 4;
    n -= 4;
  }
  if (n >= 2) {
    h += load_le(p, 2) * k3;
    h ^= rotr64(h, 48) * k1;
    p += 2;
    n -= 2;
  }
  if (n >= 1) {
    h += static_cast<unsigned char>(*p) * k3;
    h ^= rotr64(h, 37) * k1;
  }
  h ^= rotr64(h, 28);
  h *= k0;
  h ^= rotr64(h, 29);
  return h;
}

// Strict float parse matching the Python/Go rules: full consumption, no
// whitespace or underscores, finite. Fast path decodes the overwhelmingly
// common statsd shapes ([-]digits[.digits], ≤15 significant digits)
// without the std::string/strtod detour (~2x parser speedup on tagged
// lines); everything else (exponents, inf/nan/hex — mostly rejects)
// falls back to the strict strtod check.
bool parse_value_slow(std::string_view s, double* out) {
  for (char c : s) {
    if (c == '_' || std::isspace(static_cast<unsigned char>(c))) return false;
    // strtod accepts C hex floats ("0x1f"); the Python parser rejects
    // them all, and Go's ParseFloat rejects the p-less form ("0x1f")
    // while accepting "0x1p3" — a form no statsd client emits, so
    // rejecting every hex literal keeps the two in-repo parsers exact
    if (c == 'x' || c == 'X') return false;
  }
  std::string buf(s);
  char* end = nullptr;
  double v = std::strtod(buf.c_str(), &end);
  if (end != buf.c_str() + buf.size()) return false;
  if (!std::isfinite(v)) return false;
  *out = v;
  return true;
}

bool parse_value(std::string_view s, double* out) {
  if (s.empty()) return false;
  const char* p = s.data();
  const char* end = p + s.size();
  bool neg = false;
  if (*p == '-') {
    neg = true;
    ++p;
  }
  uint64_t mant = 0;
  int digits = 0, frac = 0;
  bool seen_dot = false, seen_digit = false;
  for (; p < end; ++p) {
    char c = *p;
    if (c >= '0' && c <= '9') {
      seen_digit = true;
      if (++digits > 15) return parse_value_slow(s, out);
      mant = mant * 10 + static_cast<uint64_t>(c - '0');
      if (seen_dot) ++frac;
    } else if (c == '.' && !seen_dot) {
      seen_dot = true;
    } else {
      return parse_value_slow(s, out);  // exponent/inf/garbage
    }
  }
  if (!seen_digit) return parse_value_slow(s, out);
  static const double kPow10[16] = {
      1e0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7,
      1e8, 1e9, 1e10, 1e11, 1e12, 1e13, 1e14, 1e15};
  double v = static_cast<double>(mant) / kPow10[frac];
  *out = neg ? -v : v;
  return true;
}

enum MetricKind : int32_t {
  KIND_COUNTER = 0,
  KIND_GAUGE = 1,
  KIND_HISTOGRAM = 2,
  KIND_TIMER = 3,
  KIND_SET = 4,
};

enum ScopeClass : int32_t {
  SCOPE_MIXED = 0,
  SCOPE_LOCAL = 1,
  SCOPE_GLOBAL = 2,
};

const char* kind_type_string(MetricKind k) {
  switch (k) {
    case KIND_COUNTER: return "counter";
    case KIND_GAUGE: return "gauge";
    case KIND_HISTOGRAM: return "histogram";
    case KIND_TIMER: return "timer";
    case KIND_SET: return "set";
  }
  return "";
}

// scope label per WorkerMetrics.Upsert routing (worker.go:108-177)
ScopeClass classify(MetricKind kind, int scope /*0 mixed,1 local,2 global*/) {
  switch (kind) {
    case KIND_COUNTER:
    case KIND_GAUGE:
      return scope == 2 ? SCOPE_GLOBAL : SCOPE_MIXED;
    case KIND_HISTOGRAM:
    case KIND_TIMER:
      if (scope == 1) return SCOPE_LOCAL;
      if (scope == 2) return SCOPE_GLOBAL;
      return SCOPE_MIXED;
    case KIND_SET:
      return scope == 1 ? SCOPE_LOCAL : SCOPE_MIXED;
  }
  return SCOPE_MIXED;
}

struct NewSeries {
  int32_t pool;  // 0 histo, 1 set, 2 counter, 3 gauge
  int32_t row;
  int32_t kind;
  int32_t scope_class;
  std::string name;
  std::string joined_tags;
};

// Open-addressing directory: identity = (kind-type string, scope class,
// name, joined tags), hashed with the same fnv1a32 digest as parse time.
struct Directory {
  struct Slot {
    uint64_t key_hash = 0;
    int32_t row = -1;
    uint32_t key_off = 0;
    uint32_t key_len = 0;
  };
  std::vector<Slot> slots;
  std::string arena;
  size_t used = 0;

  Directory() : slots(1 << 12) {}

  void reset() {
    slots.assign(1 << 12, Slot{});
    arena.clear();
    used = 0;
  }

  void grow() {
    std::vector<Slot> old;
    old.swap(slots);
    slots.assign(old.size() * 2, Slot{});
    for (const Slot& s : old) {
      if (s.row >= 0) {
        size_t mask = slots.size() - 1;
        size_t i = s.key_hash & mask;
        while (slots[i].row >= 0) i = (i + 1) & mask;
        slots[i] = s;
      }
    }
  }

  // returns row; *created set when the series is new. next_row supplies
  // the row id for a new series. Identity is passed as PARTS — compared
  // piecewise against the arena and appended with the canonical
  // `name \x1f type \x1f joined \x1f cls` layout only on a miss, so the
  // per-line hot path never builds a key string (round-5 parse bench:
  // the key build + byte-serial fnv1a64 full-key pass were ~25% of
  // commit cost).
  int32_t upsert_parts(uint64_t key_hash, std::string_view name,
                       std::string_view type_str, std::string_view joined,
                       char cls_char, int32_t next_row, bool* created) {
    if (used * 4 >= slots.size() * 3) grow();
    size_t mask = slots.size() - 1;
    const size_t nn = name.size(), nt = type_str.size(), nj = joined.size();
    const size_t want = nn + nt + nj + 4;
    size_t i = key_hash & mask;
    while (slots[i].row >= 0) {
      if (slots[i].key_hash == key_hash && slots[i].key_len == want) {
        const char* k = arena.data() + slots[i].key_off;
        if (std::memcmp(k, name.data(), nn) == 0 && k[nn] == '\x1f' &&
            std::memcmp(k + nn + 1, type_str.data(), nt) == 0 &&
            k[nn + 1 + nt] == '\x1f' &&
            std::memcmp(k + nn + 2 + nt, joined.data(), nj) == 0 &&
            k[want - 2] == '\x1f' && k[want - 1] == cls_char) {
          *created = false;
          return slots[i].row;
        }
      }
      i = (i + 1) & mask;
    }
    slots[i].key_hash = key_hash;
    slots[i].row = next_row;
    slots[i].key_off = static_cast<uint32_t>(arena.size());
    slots[i].key_len = static_cast<uint32_t>(want);
    arena.append(name);
    arena.push_back('\x1f');
    arena.append(type_str);
    arena.push_back('\x1f');
    arena.append(joined);
    arena.push_back('\x1f');
    arena.push_back(cls_char);
    ++used;
    *created = true;
    return next_row;
  }
};

// Directory key hash from the identity PARTS — no key-string build.
// metro64 (8 bytes/step) replaces the old byte-serial fnv1a64 pass over
// the built key on the per-line hot path. Purely internal (the
// directory lives one interval and the hash is never serialized), but
// every producer must agree — ingest commit, vn_upsert, vn_upsert_many
// — since the directory dedupes by this hash + piecewise compare.
inline uint64_t dir_key_hash(uint32_t digest, std::string_view name,
                             std::string_view type_str,
                             std::string_view joined, int cls) {
  uint64_t h = metro_hash64(name, 0x56454E55ull);  // "VENU"
  uint64_t hj = metro_hash64(joined, 0x544147ull);  // "TAG"
  h ^= (hj << 17) | (hj >> 47);
  h ^= (static_cast<uint64_t>(digest) << 32) ^
       (static_cast<uint64_t>(type_str.size()) << 8) ^
       static_cast<uint64_t>(cls);
  return fmix64(h);
}

struct Ctx {
  int hll_precision = 14;
  bool set_hash_metro = false;

  // Guards every mutation; taken by all exported entry points so readers
  // calling vn_ingest_routed can commit into any shard while the Python
  // flush path drains another. Parsing never holds it (thread-local
  // scratch), so it only covers the short directory-upsert + SoA append.
  // Recursive so the flush path can hold it across its whole multi-call
  // drain→sync→reset sequence (vn_lock/vn_unlock) — otherwise a routed
  // commit slipping between the last drain and the reset would be
  // destroyed with the old epoch.
  std::recursive_mutex mu;

  Directory dir;
  int32_t next_histo_row = 0;
  int32_t next_set_row = 0;
  int32_t next_counter_row = 0;
  int32_t next_gauge_row = 0;

  // Raw-sample staging plane (round-4 staged ingest): histo/timer
  // samples land here at parse time and Python detaches the whole plane
  // once per flush (vn_stage_detach) — zero per-batch Python work. Rows
  // whose staging is full spill into the h_* SoA batch below, which
  // Python drains mid-interval and folds directly (hot rows keep the
  // gathered per-batch fold cheap). Heap-allocated so detach is a
  // pointer handoff: Python wraps the vectors' memory as numpy, uploads,
  // then vn_stage_free()s the plane.
  struct StagePlane {
    int32_t rows = 0;   // allocated rows (pow2-grown)
    int32_t depth = 0;  // slots per row (B)
    long long total = 0;  // staged samples since allocation
    // true while every staged weight is exactly 1.0 (unsampled metrics,
    // the overwhelmingly common case): the consumer can then skip the
    // weights plane entirely and rebuild it on device from `count` —
    // halving the host->device upload at flush
    bool unit_wts = true;
    std::vector<float> vals;     // [rows * depth]
    std::vector<float> wts;      // [rows * depth]
    std::vector<int32_t> count;  // [rows]
    // micro-fold watermark: slots [drained[r], count[r]) are staged but
    // not yet copied out by vn_stage_drain_delta. `count` itself is
    // never rewound by a drain — the per-epoch depth cap (and hence the
    // spill partitioning) is identical whether or not micro-folds ran.
    std::vector<int32_t> drained;  // [rows], lazily sized
    long long drained_total = 0;
  };
  int stage_depth = 0;  // 0 = staging disabled (legacy SoA only)
  StagePlane* stage = nullptr;

  // pending SoA batches
  std::vector<int32_t> h_rows;
  std::vector<float> h_vals;
  std::vector<float> h_wts;
  std::vector<int32_t> c_rows;
  std::vector<double> c_contribs;
  std::vector<int32_t> g_rows;
  std::vector<double> g_vals;
  // built lazily the first time g_rows hits the spill cap: gauges are
  // last-write-wins, so a capped batch must UPDATE a row's pending
  // entry in place rather than shed the newest value (a shed gauge
  // would flush an actively wrong early-interval value). Cleared on
  // drain/reset; rows absent from the capped batch still shed+count.
  std::unordered_map<int32_t, size_t> g_last;
  std::vector<int32_t> s_rows;
  std::vector<int32_t> s_idx;
  std::vector<int8_t> s_rank;

  std::vector<NewSeries> new_series;
  std::string other_lines;  // events/_sc handed back to Python, \n-joined

  long long processed = 0;
  long long errors = 0;
  long long overload_dropped = 0;  // samples shed at the SoA spill caps
  size_t spill_cap = size_t{1} << 22;  // entries per pending SoA batch

  // Commit-path lock contention stats (vn_lock_stats; recorded only
  // while vn_set_lock_stats(1) — the try_lock probe and clock reads cost
  // ~10-20% of per-line budget, so the hot path skips them by default).
  // Sample rings keep the most recent waits/holds for true percentiles.
  long long lk_acquisitions = 0;
  long long lk_contended = 0;
  long long lk_wait_ns_total = 0;
  long long lk_hold_ns_total = 0;
  static constexpr int kLockRing = 4096;
  int32_t lk_ring_n = 0;  // total samples ever (ring index = n % kLockRing)
  int64_t lk_wait_ring[kLockRing] = {0};
  int64_t lk_hold_ring[kLockRing] = {0};

  // SSF span ingest stats (native span→metric fast path). Service names
  // come from untrusted payloads — keyed by hash map so per-span cost
  // stays O(1) under high service cardinality.
  long long ssf_spans = 0;
  long long ssf_invalid = 0;
  std::unordered_map<std::string, long long> ssf_services;
  std::string ssf_services_out;  // drained lines awaiting pickup
  // raw SSF payloads the native reader could not ingest (STATUS samples
  // aboard -> Python path). Bounded; overflow counts into ssf_invalid.
  std::vector<std::string> ssf_fallback;
  size_t ssf_fallback_bytes = 0;
  static constexpr size_t kSsfFallbackCap = 1 << 22;
  uint64_t uniq_rng = 0x9E3779B97F4A7C15ull;

  // scratch reused across lines (SSF extraction builds `joined` itself;
  // DogStatsD tag parsing uses the thread-local Scratch instead)
  std::string joined;
};

bool route_metric(Ctx* ctx, std::string_view name, MetricKind kind,
                  double value, std::string_view set_value,
                  double sample_rate, int scope);

// Parse-phase scratch, one per reader thread: parsing (tag sort/join —
// the expensive part of a line) runs with no lock held; only the commit
// into the target shard takes that shard's mutex.
struct Scratch {
  std::vector<std::string_view> tags;
  std::string joined;
};

struct Parsed {
  std::string_view name;
  MetricKind kind = KIND_COUNTER;
  double value = 0;
  std::string_view set_value;
  double sample_rate = 1.0;
  int scope = 0;
  uint32_t digest = 0;  // worker-routing digest (fnv1a32 of identity)
};

bool commit_metric(Ctx* ctx, const Parsed& p, const std::string& joined);

// Delimiter finders: one tokenizer body (parse_line_impl), two ways to
// locate delimiters. MaskFinder covers lines ≤64 bytes (the production
// norm — avg ~50B) with ONE SSE2 sweep building '|' ':' ',' bitmasks,
// replacing ~5 memchr calls' worth of per-call overhead; ScalarFinder
// is the memchr path for longer lines. Both must locate identically —
// the shared body is what guarantees the accept/reject sets match
// (pinned by tools/fuzz_differential.py's dogstatsd target).
struct ScalarFinder {
  std::string_view line;
  size_t first_colon() const { return line.find(':'); }
  size_t next_pipe(size_t from) const { return line.find('|', from); }
  size_t next_comma(size_t from, size_t limit) const {
    size_t c = line.find(',', from);
    return (c == std::string_view::npos || c >= limit)
               ? std::string_view::npos
               : c;
  }
};

struct MaskFinder {
  uint64_t pipe = 0, colon = 0, comma = 0;

  explicit MaskFinder(std::string_view line) {
    const char* p = line.data();
    const size_t n = line.size();
    const __m128i vp = _mm_set1_epi8('|');
    const __m128i vc = _mm_set1_epi8(':');
    const __m128i vm = _mm_set1_epi8(',');
    size_t i = 0;
    for (; i + 16 <= n; i += 16) {
      __m128i x = _mm_loadu_si128(
          reinterpret_cast<const __m128i*>(p + i));
      pipe |= static_cast<uint64_t>(static_cast<uint16_t>(
                  _mm_movemask_epi8(_mm_cmpeq_epi8(x, vp))))
              << i;
      colon |= static_cast<uint64_t>(static_cast<uint16_t>(
                   _mm_movemask_epi8(_mm_cmpeq_epi8(x, vc))))
               << i;
      comma |= static_cast<uint64_t>(static_cast<uint16_t>(
                   _mm_movemask_epi8(_mm_cmpeq_epi8(x, vm))))
               << i;
    }
    for (; i < n; ++i) {  // tail (never reads past the buffer)
      const char c = p[i];
      if (c == '|') pipe |= 1ull << i;
      else if (c == ':') colon |= 1ull << i;
      else if (c == ',') comma |= 1ull << i;
    }
  }

  static size_t from_mask(uint64_t m) {
    return m ? static_cast<size_t>(__builtin_ctzll(m))
             : std::string_view::npos;
  }
  size_t first_colon() const { return from_mask(colon); }
  size_t next_pipe(size_t from) const {
    // from <= 64 always (one past a delimiter in a ≤64B line)
    return from_mask(from >= 64 ? 0 : pipe & (~0ull << from));
  }
  size_t next_comma(size_t from, size_t limit) const {
    uint64_t m = from >= 64 ? 0 : comma & (~0ull << from);
    if (limit < 64) m &= (1ull << limit) - 1;
    return from_mask(m);
  }
};

template <class Finder>
inline bool parse_line_impl(const Finder& f, Scratch* sc,
                            std::string_view line, Parsed* out) {
  size_t colon = f.first_colon();
  if (colon == std::string_view::npos || colon == 0) return false;
  std::string_view name = line.substr(0, colon);
  // the reference tokenizes by splitting on '|' FIRST (pipeSplitter,
  // samplers/parser.go:298-325): the first pipe chunk must be the full
  // name:value, so a '|' before the first ':' means the first chunk
  // has no colon — reject like the reference and the Python parser do
  // (round-4 differential fuzz, tools/fuzz_differential.py). One scan:
  // the global first '|' past the colon IS pipe1.
  size_t pipe1 = f.next_pipe(0);
  if (pipe1 == std::string_view::npos || pipe1 < colon) return false;
  std::string_view value_chunk = line.substr(colon + 1, pipe1 - colon - 1);
  size_t pipe2 = f.next_pipe(pipe1 + 1);
  std::string_view type_chunk =
      line.substr(pipe1 + 1, (pipe2 == std::string_view::npos
                                  ? line.size()
                                  : pipe2) - pipe1 - 1);
  if (type_chunk.empty()) return false;

  MetricKind kind;
  switch (type_chunk[0]) {
    case 'c': kind = KIND_COUNTER; break;
    case 'g': kind = KIND_GAUGE; break;
    case 'd':
    case 'h': kind = KIND_HISTOGRAM; break;
    case 'm': kind = KIND_TIMER; break;
    case 's': kind = KIND_SET; break;
    default: return false;
  }

  double value = 0;
  std::string_view set_value;
  if (kind == KIND_SET) {
    set_value = value_chunk;
  } else {
    if (!parse_value(value_chunk, &value)) return false;
  }

  double sample_rate = 1.0;
  bool found_rate = false, found_tags = false;
  int scope = 0;
  sc->tags.clear();
  sc->joined.clear();

  size_t pos = pipe2;
  while (pos != std::string_view::npos) {
    size_t next = f.next_pipe(pos + 1);
    size_t chunk_end = next == std::string_view::npos ? line.size() : next;
    std::string_view chunk = line.substr(pos + 1, chunk_end - pos - 1);
    if (chunk.empty()) return false;
    if (chunk[0] == '@') {
      if (found_rate) return false;
      if (!parse_value(chunk.substr(1), &sample_rate)) return false;
      if (!(sample_rate > 0 && sample_rate <= 1)) return false;
      found_rate = true;
    } else if (chunk[0] == '#') {
      if (found_tags) return false;
      found_tags = true;
      size_t tstart = pos + 2;  // one past '#'
      while (true) {
        size_t comma = f.next_comma(tstart, chunk_end);
        size_t e = comma == std::string_view::npos ? chunk_end : comma;
        sc->tags.push_back(line.substr(tstart, e - tstart));
        if (comma == std::string_view::npos) break;
        tstart = comma + 1;
      }
      std::sort(sc->tags.begin(), sc->tags.end());
      // first magic scope tag (prefix match) is consumed
      // (samplers/parser.go:394-408)
      for (size_t i = 0; i < sc->tags.size(); ++i) {
        constexpr std::string_view kLocal = "veneurlocalonly";
        constexpr std::string_view kGlobal = "veneurglobalonly";
        if (sc->tags[i].substr(0, kLocal.size()) == kLocal) {
          scope = 1;
          sc->tags.erase(sc->tags.begin() + i);
          break;
        }
        if (sc->tags[i].substr(0, kGlobal.size()) == kGlobal) {
          scope = 2;
          sc->tags.erase(sc->tags.begin() + i);
          break;
        }
      }
      for (size_t i = 0; i < sc->tags.size(); ++i) {
        if (i) sc->joined.push_back(',');
        sc->joined.append(sc->tags[i]);
      }
    } else {
      return false;
    }
    pos = next;
  }

  out->name = name;
  out->kind = kind;
  out->value = value;
  out->set_value = set_value;
  out->sample_rate = sample_rate;
  out->scope = scope;
  // identity digest: fnv1a32 over name, type, joined tags (parse-time
  // digest, samplers/parser.go:325-420); doubles as the shard router
  uint32_t digest = fnv1a32(name);
  digest = fnv1a32(kind_type_string(kind), digest);
  digest = fnv1a32(sc->joined, digest);
  out->digest = digest;
  return true;
}

// Parse one metric line into `out` (tags normalized into sc->joined);
// returns false on parse error. No ctx access — safe concurrently.
bool parse_line(Scratch* sc, std::string_view line, Parsed* out) {
  if (line.size() <= 64) {
    return parse_line_impl(MaskFinder(line), sc, line, out);
  }
  return parse_line_impl(ScalarFinder{line}, sc, line, out);
}

// Parse one metric line and commit it into ctx (single-shard path).
bool handle_line(Ctx* ctx, std::string_view line) {
  thread_local Scratch sc;
  Parsed p;
  if (!parse_line(&sc, line, &p)) return false;
  return commit_metric(ctx, p, sc.joined);
}

// Route one parsed/converted sample into the pools. Expects ctx->joined to
// hold the sorted, magic-stripped tag string. Used by the SSF span
// extraction below (which builds ctx->joined itself); the DogStatsD text
// path goes parse_line → commit_metric.
bool route_metric(Ctx* ctx, std::string_view name, MetricKind kind,
                  double value, std::string_view set_value,
                  double sample_rate, int scope) {
  Parsed p;
  p.name = name;
  p.kind = kind;
  p.value = value;
  p.set_value = set_value;
  p.sample_rate = sample_rate;
  p.scope = scope;
  uint32_t digest = fnv1a32(name);
  digest = fnv1a32(kind_type_string(kind), digest);
  digest = fnv1a32(ctx->joined, digest);
  p.digest = digest;
  return commit_metric(ctx, p, ctx->joined);
}

// Commit one parsed metric into a shard's directory + SoA buffers.
// Caller holds ctx->mu (or owns the ctx exclusively).
// Store one histo/timer sample into the staging plane. Returns false if
// staging is disabled or the row's slots are full (caller spills to the
// SoA batch). Caller holds the ctx mutex.
bool stage_histo_sample(Ctx* ctx, int32_t row, double value,
                        double sample_rate) {
  if (ctx->stage_depth <= 0) return false;
  Ctx::StagePlane* sp = ctx->stage;
  if (sp == nullptr) {
    sp = ctx->stage = new Ctx::StagePlane();
    sp->depth = ctx->stage_depth;
  }
  if (row >= sp->rows) {
    int32_t nr = sp->rows > 0 ? sp->rows : 4096;
    while (nr <= row) nr *= 2;
    // resize appends zeroed slots; row-major [rows, depth] layout means
    // existing rows keep their offsets
    sp->vals.resize(static_cast<size_t>(nr) * sp->depth, 0.0f);
    sp->wts.resize(static_cast<size_t>(nr) * sp->depth, 0.0f);
    sp->count.resize(nr, 0);
    sp->rows = nr;
  }
  int32_t& c = sp->count[row];
  if (c >= sp->depth) return false;
  size_t at = static_cast<size_t>(row) * sp->depth + c;
  float w = static_cast<float>(1.0 / sample_rate);
  sp->vals[at] = static_cast<float>(value);
  sp->wts[at] = w;
  if (w != 1.0f) sp->unit_wts = false;
  ++c;
  ++sp->total;
  return true;
}

bool commit_metric(Ctx* ctx, const Parsed& p, const std::string& joined) {
  std::string_view name = p.name;
  MetricKind kind = p.kind;
  double value = p.value;
  std::string_view set_value = p.set_value;
  double sample_rate = p.sample_rate;
  const char* type_str = kind_type_string(kind);
  ScopeClass cls = classify(kind, p.scope);

  // directory key spans identity + scope class (the same MetricKey can
  // legally live in two scope maps); hashed from parts, no key build
  const char cls_char = static_cast<char>('0' + cls);
  uint64_t key_hash = dir_key_hash(p.digest, name, type_str, joined, cls);

  bool created = false;
  int32_t row;
  int32_t pool;
  // Overload shedding: the pending SoA batches are normally drained
  // every ~100ms (Server's native pump / strided ingest checks), but a
  // host whose aggregate throughput is below the offered load can't
  // drain them at arrival rate, and an unbounded vector here is an OOM
  // waiting for a traffic spike (observed: multi-GB RSS in an overload
  // soak). Beyond the cap the SAMPLE is dropped and counted
  // (overload_dropped -> veneur.ingest.overload_dropped_total); the
  // series registration above the drop still happens, so cardinality
  // bookkeeping stays exact. Mirrors the reference's bounded worker
  // channels, where the kernel socket buffer sheds the excess
  // (worker.go:31-48 PacketChan; drop-don't-block per README backpressure).
  const size_t kSpillCap = ctx->spill_cap;
  switch (kind) {
    case KIND_HISTOGRAM:
    case KIND_TIMER: {
      pool = 0;
      row = ctx->dir.upsert_parts(key_hash, name, type_str, joined,
                                  cls_char, ctx->next_histo_row, &created);
      if (created) ++ctx->next_histo_row;
      if (!stage_histo_sample(ctx, row, value, sample_rate)) {
        // staging disabled, or this row's plane slots are full: spill
        // into the SoA batch for the direct per-batch device fold
        if (ctx->h_rows.size() < kSpillCap) {
          ctx->h_rows.push_back(row);
          ctx->h_vals.push_back(static_cast<float>(value));
          ctx->h_wts.push_back(static_cast<float>(1.0 / sample_rate));
        } else {
          ++ctx->overload_dropped;
        }
      }
      break;
    }
    case KIND_SET: {
      pool = 1;
      row = ctx->dir.upsert_parts(key_hash, name, type_str, joined,
                                  cls_char, ctx->next_set_row,
                                  &created);
      if (created) ++ctx->next_set_row;
      uint64_t h = ctx->set_hash_metro ? metro_hash64(set_value, 1337)
                                       : fmix64(fnv1a64(set_value));
      int p = ctx->hll_precision;
      uint32_t idx = static_cast<uint32_t>(h >> (64 - p));
      uint64_t w = h << p;
      int rank = w == 0 ? (64 - p + 1) : (__builtin_clzll(w) + 1);
      if (rank > 64 - p + 1) rank = 64 - p + 1;
      if (ctx->s_rows.size() < kSpillCap) {
        ctx->s_rows.push_back(row);
        ctx->s_idx.push_back(static_cast<int32_t>(idx));
        ctx->s_rank.push_back(static_cast<int8_t>(rank));
      } else {
        ++ctx->overload_dropped;
      }
      break;
    }
    case KIND_COUNTER: {
      pool = 2;
      row = ctx->dir.upsert_parts(key_hash, name, type_str, joined,
                                  cls_char, ctx->next_counter_row, &created);
      if (created) ++ctx->next_counter_row;
      if (ctx->c_rows.size() < kSpillCap) {
        // Go semantics: int64(sample) * int64(1/rate)
        ctx->c_rows.push_back(row);
        ctx->c_contribs.push_back(
            static_cast<double>(static_cast<long long>(value) *
                                static_cast<long long>(1.0 / sample_rate)));
      } else {
        ++ctx->overload_dropped;
      }
      break;
    }
    case KIND_GAUGE: {
      pool = 3;
      row = ctx->dir.upsert_parts(key_hash, name, type_str, joined,
                                  cls_char, ctx->next_gauge_row, &created);
      if (created) ++ctx->next_gauge_row;
      if (ctx->g_rows.size() < kSpillCap) {
        ctx->g_rows.push_back(row);
        ctx->g_vals.push_back(value);
      } else {
        if (ctx->g_last.empty()) {
          // overload onset: index the batch once (last occurrence wins)
          for (size_t i = 0; i < ctx->g_rows.size(); ++i)
            ctx->g_last[ctx->g_rows[i]] = i;
        }
        auto it = ctx->g_last.find(row);
        if (it != ctx->g_last.end()) {
          ctx->g_vals[it->second] = value;  // last write wins, in place
        } else {
          ++ctx->overload_dropped;
        }
      }
      break;
    }
  }
  if (created) {
    NewSeries ns;
    ns.pool = pool;
    ns.row = row;
    ns.kind = kind;
    ns.scope_class = cls;
    ns.name.assign(name);
    ns.joined_tags = joined;
    // the drain protocol (vn_drain_new_series) frames records with the
    // \x1e/\x1f unit separators; no legitimate name/tag contains them,
    // but wire input is untrusted — substitute so framing can't break
    for (char& ch : ns.name)
      if (ch == '\x1e' || ch == '\x1f') ch = '_';
    for (char& ch : ns.joined_tags)
      if (ch == '\x1e' || ch == '\x1f') ch = '_';
    ctx->new_series.push_back(std::move(ns));
  }
  return true;
}

// ---------------------------------------------------------------------------
// SSF span ingest: protobuf wire decode + span→metric extraction.
//
// Replaces the Python path (protocol/ssf_wire.parse_ssf +
// core/spans.MetricExtractionSink) for the hot case — spans carrying
// counter/gauge/histogram/set samples and indicator timers (reference
// sinks/ssfmetrics/metrics.go:66-141, samplers/parser.go:103-208). The
// decoder is a minimal hand-rolled proto3 reader over proto/ssf.proto
// (field numbers follow the public SSF spec, ssf/sample.proto), reading
// string fields as zero-copy views into the datagram. STATUS samples are
// control-plane traffic; spans carrying them return -1 so the caller can
// take the Python path.

struct TagPair {
  std::string_view k, v;
};

struct SampleView {
  int metric = 0;  // SSFSample.Metric enum
  std::string_view name;
  float value = 0;
  std::string_view message;
  int status = 0;
  float sample_rate = 1.0f;
  int scope = 0;  // SSFSample.Scope enum
  std::vector<TagPair> tags;
};

struct SpanView {
  int64_t trace_id = 0, id = 0, parent_id = 0;
  int64_t start_ts = 0, end_ts = 0;
  bool error = false, indicator = false;
  std::string_view service, name;
  std::vector<TagPair> tags;
  std::vector<SampleView> samples;
  bool has_status = false;
};

// proto3 `string` fields must be valid UTF-8; the stock protobuf
// decoders (Python, Go) reject violations, so the fast path must too
// or corrupted packets would diverge between the two pipelines.
bool valid_utf8(std::string_view s) {
  size_t i = 0, n = s.size();
  while (i < n) {
    uint8_t c = static_cast<uint8_t>(s[i]);
    if (c < 0x80) { i++; continue; }
    int len;
    uint32_t cp, min_cp;
    if ((c >> 5) == 0x6) { len = 2; cp = c & 0x1F; min_cp = 0x80; }
    else if ((c >> 4) == 0xE) { len = 3; cp = c & 0x0F; min_cp = 0x800; }
    else if ((c >> 3) == 0x1E) { len = 4; cp = c & 0x07; min_cp = 0x10000; }
    else return false;
    if (i + static_cast<size_t>(len) > n) return false;
    for (int j = 1; j < len; j++) {
      uint8_t cc = static_cast<uint8_t>(s[i + j]);
      if ((cc >> 6) != 0x2) return false;
      cp = (cp << 6) | (cc & 0x3F);
    }
    if (cp < min_cp || cp > 0x10FFFF) return false;
    if (cp >= 0xD800 && cp <= 0xDFFF) return false;  // surrogate range
    i += len;
  }
  return true;
}

struct ProtoReader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      // overflow in the 10th byte (bits past 2^64) is malformed wire;
      // see WireCursor::varint
      if (shift == 63 && (b & 0xfe)) {
        ok = false;
        return 0;
      }
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  // a TAG varint: canonical wire caps tags at 5 bytes (uint32), as
  // upstream protobuf parsers enforce. The reference's gogo-generated
  // Unmarshal is looser (≤10 bytes, truncating) — deliberate
  // spec-over-reference divergence, see PARITY.md "Deliberate
  // wire-strictness divergences"
  uint64_t tag_varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 35) {
      uint8_t b = *p++;
      if (shift == 28 && (b & 0xF0)) {  // bits past 2^32 or a 6th byte
        ok = false;
        return 0;
      }
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  std::string_view bytes() {
    uint64_t n = varint();
    if (!ok || n > static_cast<uint64_t>(end - p)) {
      ok = false;
      return {};
    }
    std::string_view s(reinterpret_cast<const char*>(p),
                       static_cast<size_t>(n));
    p += n;
    return s;
  }

  // a `string`-typed field: length-delimited AND valid UTF-8
  std::string_view str() {
    std::string_view s = bytes();
    if (ok && !valid_utf8(s)) ok = false;
    return s;
  }

  float fixed32f() {
    if (end - p < 4) {
      ok = false;
      return 0;
    }
    float f;
    std::memcpy(&f, p, 4);
    p += 4;
    return f;
  }

  void skip(int wire_type) {
    switch (wire_type) {
      case 0: varint(); break;
      case 1: p += 8; if (p > end) ok = false; break;
      case 2: bytes(); break;
      case 5: p += 4; if (p > end) ok = false; break;
      default: ok = false;
    }
  }
};

// A known field whose declared wire type doesn't match the schema is a
// corrupt/incompatible packet: reject it (the Python protobuf parser
// raises; silently consuming with the wrong reader would desync the
// stream and ingest garbage into the series directory).
#define VN_EXPECT_WT(want) \
  if (wt != (want)) return false

// map<string,string> entry: {1: key, 2: value}
bool decode_tag_entry(std::string_view buf, TagPair* out) {
  ProtoReader r{reinterpret_cast<const uint8_t*>(buf.data()),
                reinterpret_cast<const uint8_t*>(buf.data() + buf.size())};
  while (r.ok && r.p < r.end) {
    uint64_t tag = r.tag_varint();
    if (!r.ok) return false;
    // field number 0 is forbidden (tag_varint already bounds the tag
    // itself at uint32, i.e. field <= 2^29-1)
    if ((tag >> 3) == 0) return false;
    int field = static_cast<int>(tag >> 3), wt = static_cast<int>(tag & 7);
    if (field == 1) {
      VN_EXPECT_WT(2);
      out->k = r.str();
    } else if (field == 2) {
      VN_EXPECT_WT(2);
      out->v = r.str();
    } else {
      r.skip(wt);
    }
  }
  return r.ok;
}

bool decode_sample(std::string_view buf, SampleView* s) {
  ProtoReader r{reinterpret_cast<const uint8_t*>(buf.data()),
                reinterpret_cast<const uint8_t*>(buf.data() + buf.size())};
  while (r.ok && r.p < r.end) {
    uint64_t tag = r.tag_varint();
    if (!r.ok) return false;
    // field number 0 is forbidden (tag_varint already bounds the tag
    // itself at uint32, i.e. field <= 2^29-1)
    if ((tag >> 3) == 0) return false;
    int field = static_cast<int>(tag >> 3), wt = static_cast<int>(tag & 7);
    switch (field) {
      case 1: VN_EXPECT_WT(0); s->metric = static_cast<int>(r.varint());
        break;
      case 2: VN_EXPECT_WT(2); s->name = r.str(); break;
      case 3: VN_EXPECT_WT(5); s->value = r.fixed32f(); break;
      case 5: VN_EXPECT_WT(2); s->message = r.str(); break;
      case 6: VN_EXPECT_WT(0); s->status = static_cast<int>(r.varint());
        break;
      case 7: VN_EXPECT_WT(5); s->sample_rate = r.fixed32f(); break;
      case 8: {
        VN_EXPECT_WT(2);
        TagPair t;
        if (!decode_tag_entry(r.bytes(), &t)) return false;
        s->tags.push_back(t);
        break;
      }
      // unit (field 9) is unused here but is a proto3 string: its bytes
      // must still be valid UTF-8 or the stock decoders reject the span
      case 9: VN_EXPECT_WT(2); r.str(); break;
      case 10: VN_EXPECT_WT(0); s->scope = static_cast<int>(r.varint());
        break;
      default: r.skip(wt);
    }
  }
  if (s->sample_rate == 0) s->sample_rate = 1.0f;  // wire normalization
  return r.ok;
}

bool decode_span(std::string_view buf, SpanView* sp) {
  ProtoReader r{reinterpret_cast<const uint8_t*>(buf.data()),
                reinterpret_cast<const uint8_t*>(buf.data() + buf.size())};
  while (r.ok && r.p < r.end) {
    uint64_t tag = r.tag_varint();
    if (!r.ok) return false;
    // field number 0 is forbidden (tag_varint already bounds the tag
    // itself at uint32, i.e. field <= 2^29-1)
    if ((tag >> 3) == 0) return false;
    int field = static_cast<int>(tag >> 3), wt = static_cast<int>(tag & 7);
    switch (field) {
      case 2: VN_EXPECT_WT(0);
        sp->trace_id = static_cast<int64_t>(r.varint());
        break;
      case 3: VN_EXPECT_WT(0); sp->id = static_cast<int64_t>(r.varint());
        break;
      case 4: VN_EXPECT_WT(0);
        sp->parent_id = static_cast<int64_t>(r.varint());
        break;
      case 5: VN_EXPECT_WT(0);
        sp->start_ts = static_cast<int64_t>(r.varint());
        break;
      case 6: VN_EXPECT_WT(0);
        sp->end_ts = static_cast<int64_t>(r.varint());
        break;
      case 7: VN_EXPECT_WT(0); sp->error = r.varint() != 0; break;
      case 8: VN_EXPECT_WT(2); sp->service = r.str(); break;
      case 10: {
        VN_EXPECT_WT(2);
        SampleView s;
        if (!decode_sample(r.bytes(), &s)) return false;
        if (s.metric == 4) sp->has_status = true;
        sp->samples.push_back(std::move(s));
        break;
      }
      case 11: {
        VN_EXPECT_WT(2);
        TagPair t;
        if (!decode_tag_entry(r.bytes(), &t)) return false;
        sp->tags.push_back(t);
        break;
      }
      case 12: VN_EXPECT_WT(0); sp->indicator = r.varint() != 0; break;
      case 13: VN_EXPECT_WT(2); sp->name = r.str(); break;
      default: r.skip(wt);
    }
  }
  if (!r.ok) return false;
  // wire normalization: empty span name falls back to the "name" tag
  if (sp->name.empty()) {
    for (size_t i = 0; i < sp->tags.size(); ++i) {
      if (sp->tags[i].k == "name") {
        sp->name = sp->tags[i].v;
        sp->tags.erase(sp->tags.begin() + i);
        break;
      }
    }
  }
  return true;
}

// "k1:v1" < "k2:v2" without materializing the joined strings. Bytes
// compare UNSIGNED — matching Python's code-point sort and
// std::string_view's char_traits compare — or non-ASCII tags would order
// differently per ingest path and split one series into two digests.
bool tagpair_less(const TagPair& a, const TagPair& b) {
  size_t na = a.k.size() + 1 + a.v.size();
  size_t nb = b.k.size() + 1 + b.v.size();
  size_t n = na < nb ? na : nb;
  for (size_t i = 0; i < n; ++i) {
    unsigned char ca = static_cast<unsigned char>(
        i < a.k.size() ? a.k[i]
        : (i == a.k.size() ? ':' : a.v[i - a.k.size() - 1]));
    unsigned char cb = static_cast<unsigned char>(
        i < b.k.size() ? b.k[i]
        : (i == b.k.size() ? ':' : b.v[i - b.k.size() - 1]));
    if (ca != cb) return ca < cb;
  }
  return na < nb;
}

// Build ctx->joined from tag pairs, consuming magic scope keys (exact-key
// match in wire order — parse_metric_ssf semantics, parser.go:276-287).
void build_joined(Ctx* ctx, std::vector<TagPair>& pairs, int* scope) {
  for (size_t i = 0; i < pairs.size();) {
    if (pairs[i].k == "veneurlocalonly") {
      *scope = 1;
      pairs.erase(pairs.begin() + i);
    } else if (pairs[i].k == "veneurglobalonly") {
      *scope = 2;
      pairs.erase(pairs.begin() + i);
    } else {
      ++i;
    }
  }
  std::sort(pairs.begin(), pairs.end(), tagpair_less);
  ctx->joined.clear();
  for (size_t i = 0; i < pairs.size(); ++i) {
    if (i) ctx->joined.push_back(',');
    ctx->joined.append(pairs[i].k);
    ctx->joined.push_back(':');
    ctx->joined.append(pairs[i].v);
  }
}

bool ingest_sample(Ctx* ctx, SampleView& s) {
  if (s.name.empty()) return false;
  MetricKind kind;
  std::string_view set_value;
  double value = 0;
  switch (s.metric) {
    case 0: kind = KIND_COUNTER; value = s.value; break;
    case 1: kind = KIND_GAUGE; value = s.value; break;
    case 2: kind = KIND_HISTOGRAM; value = s.value; break;
    case 3: kind = KIND_SET; set_value = s.message; break;
    default: return false;  // STATUS handled by the Python path
  }
  int scope = 0;
  if (s.scope == 1) scope = 1;
  else if (s.scope == 2) scope = 2;
  build_joined(ctx, s.tags, &scope);
  return route_metric(ctx, s.name, kind, value, set_value,
                      s.sample_rate, scope);
}

// xorshift64* for uniqueness sampling — statistical, parity not required
// (the Python path uses random.random(), ssf/samples.go RandomlySample).
// State lives per-Ctx (no shared mutable global → no cross-context race).
inline double uniform01(uint64_t* state) {
  uint64_t x = *state;
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  *state = x;
  return static_cast<double>((x * 0x2545F4914F6CDD1Dull) >> 11) /
         static_cast<double>(1ull << 53);
}

void bump_service_count(Ctx* ctx, std::string_view service) {
  if (service.empty()) service = "unknown";
  // service names are untrusted payload bytes: bound the length (so one
  // huge name can't wedge the line-framed drain) and replace the drain
  // framing bytes themselves
  if (service.size() > 256) service = service.substr(0, 256);
  std::string key(service);
  for (char& c : key) {
    if (c == '\t' || c == '\n') c = '_';
  }
  ++ctx->ssf_services[std::move(key)];
}

// returns 1 ok, 0 decode error, -1 span carries STATUS samples (take the
// Python path; nothing was ingested)
int ingest_ssf_span(Ctx* ctx, std::string_view buf,
                    std::string_view indicator_name,
                    std::string_view objective_name, double uniq_rate) {
  SpanView sp;
  if (!decode_span(buf, &sp)) return 0;
  if (sp.has_status) return -1;

  for (SampleView& s : sp.samples) {
    if (!ingest_sample(ctx, s)) ++ctx->ssf_invalid;
  }

  bool valid_trace = sp.id != 0 && sp.trace_id != 0 && sp.start_ts != 0 &&
                     sp.end_ts != 0 && !sp.name.empty();
  if (sp.indicator && valid_trace) {
    double duration_ns = static_cast<double>(sp.end_ts - sp.start_ts);
    const std::string_view error_sv = sp.error ? "true" : "false";
    if (!indicator_name.empty()) {
      std::vector<TagPair> tags{{"service", sp.service}, {"error", error_sv}};
      int scope = 0;
      build_joined(ctx, tags, &scope);
      route_metric(ctx, indicator_name, KIND_HISTOGRAM, duration_ns, {},
                   1.0, scope);
    }
    if (!objective_name.empty()) {
      std::string_view objective = sp.name;
      for (const TagPair& t : sp.tags) {
        if (t.k == "ssf_objective" && !t.v.empty()) objective = t.v;
      }
      std::vector<TagPair> tags{{"service", sp.service},
                                {"objective", objective},
                                {"error", error_sv}};
      int scope = 2;  // veneurglobalonly
      build_joined(ctx, tags, &scope);
      route_metric(ctx, objective_name, KIND_HISTOGRAM, duration_ns, {},
                   1.0, scope);
    }
  }

  if (uniq_rate > 0 && !sp.service.empty() &&
      (uniq_rate >= 1.0 || uniform01(&ctx->uniq_rng) < uniq_rate)) {
    std::vector<TagPair> tags{
        {"indicator", sp.indicator ? "true" : "false"},
        {"service", sp.service},
        {"root_span", sp.id == sp.trace_id ? "true" : "false"}};
    int scope = 0;
    build_joined(ctx, tags, &scope);
    route_metric(ctx, "ssf.names_unique", KIND_SET, 0.0, sp.name, 1.0,
                 scope);
  }

  ++ctx->ssf_spans;
  bump_service_count(ctx, sp.service);
  return 1;
}

}  // namespace

extern "C" {

// Build stamp: the Makefile injects the sha256 prefix of this source
// file, so tests can detect a stale committed .so (one that no longer
// matches dogstatsd.cpp) instead of silently testing old code.
#ifndef VN_SOURCE_HASH
#define VN_SOURCE_HASH "unstamped"
#endif
const char* vn_source_hash() { return VN_SOURCE_HASH; }

void* vn_ctx_new(int hll_precision) {
  Ctx* ctx = new Ctx();
  ctx->hll_precision = hll_precision;
  return ctx;
}

void vn_ctx_free(void* p) {
  Ctx* ctx = static_cast<Ctx*>(p);
  delete ctx->stage;
  delete ctx;
}

// Enable the raw-sample staging plane with B slots per histogram row
// (0 disables; takes effect for subsequent samples).
void vn_set_stage_depth(void* p, int depth) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  ctx->stage_depth = depth;
}

// Detach the staging plane for flush: hands ownership of the [rows,
// depth] vals/wts planes and the per-row counts to the caller and
// installs a fresh (lazily allocated) plane for the next epoch. Returns
// an opaque handle to free with vn_stage_free AFTER the caller is done
// with the pointers, or NULL when nothing is staged.
void* vn_stage_detach(void* p, float** vals, float** wts, int32_t** counts,
                      int32_t* rows_out, int32_t* depth_out) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  Ctx::StagePlane* sp = ctx->stage;
  if (sp == nullptr || sp->total == 0) return nullptr;
  ctx->stage = nullptr;
  *vals = sp->vals.data();
  *wts = sp->wts.data();
  *counts = sp->count.data();
  *rows_out = sp->rows;
  *depth_out = sp->depth;
  return sp;
}

// Whether every weight in a detached plane is exactly 1.0 (see
// StagePlane.unit_wts). Takes the DETACHED handle, not the ctx.
int vn_stage_unit_wts(void* plane) {
  return static_cast<Ctx::StagePlane*>(plane)->unit_wts ? 1 : 0;
}

void vn_stage_free(void* plane) {
  delete static_cast<Ctx::StagePlane*>(plane);
}

// Staged-sample count (telemetry / drain-threshold checks).
long long vn_stage_total(void* p) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  return ctx->stage == nullptr ? 0 : ctx->stage->total;
}

// Staged samples not yet copied out by vn_stage_drain_delta
// (micro-fold due-threshold checks).
long long vn_stage_pending(void* p) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  Ctx::StagePlane* sp = ctx->stage;
  return sp == nullptr ? 0 : sp->total - sp->drained_total;
}

// Copy up to `cap` not-yet-drained staged samples into the caller's COO
// buffers as (row, absolute slot, val, wt) and advance the per-row
// drained watermark. `count` is untouched: the depth cap — and hence
// which samples spill to the SoA batch — is identical to a run with no
// micro-folds, which is what makes micro==batch bit-identity hold.
// Returns the number of entries written.
int64_t vn_stage_drain_delta(void* p, int32_t* rows, int32_t* slots,
                             float* vals, float* wts, int64_t cap) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  Ctx::StagePlane* sp = ctx->stage;
  if (sp == nullptr || sp->total == sp->drained_total || cap <= 0) return 0;
  if (static_cast<int32_t>(sp->drained.size()) < sp->rows)
    sp->drained.resize(sp->rows, 0);
  int64_t n = 0;
  for (int32_t r = 0; r < sp->rows && n < cap; ++r) {
    int32_t d = sp->drained[r];
    const int32_t c = sp->count[r];
    if (d >= c) continue;
    const size_t base = static_cast<size_t>(r) * sp->depth;
    for (; d < c && n < cap; ++d, ++n) {
      rows[n] = r;
      slots[n] = d;
      vals[n] = sp->vals[base + d];
      wts[n] = sp->wts[base + d];
    }
    sp->drained_total += d - sp->drained[r];
    sp->drained[r] = d;
  }
  return n;
}

// Switch the set-element hash to metro64(seed=1337) for Go-fleet interop
// (must match every other inserter of the same set series).
void vn_ctx_set_metro(void* p, int enable) {
  static_cast<Ctx*>(p)->set_hash_metro = enable != 0;
}

// Hold the context lock across a multi-call sequence (the mutex is
// recursive, so the individual exports still work while held). The flush
// path wraps its drain→sync→reset in this so no routed commit can land
// between the last drain and the reset and be destroyed with the epoch.
// ctypes releases the GIL, so blocking here cannot deadlock Python.
void vn_lock(void* p) { static_cast<Ctx*>(p)->mu.lock(); }
void vn_unlock(void* p) { static_cast<Ctx*>(p)->mu.unlock(); }

uint64_t vn_metro_hash64(const char* data, int len, uint64_t seed) {
  return metro_hash64(std::string_view(data, static_cast<size_t>(len)), seed);
}

// ---------------------------------------------------------------------------
// Forward-batch wire encoder.
//
// Emits the histogram/timer rows of a flush snapshot as protobuf wire
// bytes of veneurtpu.MetricBatch (proto/veneur_tpu.proto) — the Python
// protobuf path costs ~5us per row building Metric messages, which at
// 1M forwarded series is seconds per flush. The wire format here is
// hand-encoded (as the framework's gob and Kafka codecs are) and
// decodes with the stock generated classes; proto3 default-skipping is
// matched (zero doubles / enum 0 omitted, empty centroids omitted).
//
// Field numbers (veneur_tpu.proto):
//   MetricBatch.metrics = 1 (LEN)
//   Metric: name=1 LEN, tags=2 LEN, kind=3 VARINT, scope=4 VARINT,
//           digest=7 LEN
//   DigestValue: centroids=1 LEN, min=2 F64, max=3 F64,
//                reciprocal_sum=4 F64, compression=5 F64
//   Centroids: means=1 packed f32, weights=2 packed f32

namespace {

inline int varint_size(uint64_t v) {
  int n = 1;
  while (v >= 0x80) {
    v >>= 7;
    ++n;
  }
  return n;
}

inline void put_varint(std::string* out, uint64_t v) {
  while (v >= 0x80) {
    out->push_back(static_cast<char>((v & 0x7F) | 0x80));
    v >>= 7;
  }
  out->push_back(static_cast<char>(v));
}

inline void put_f64(std::string* out, int field, double v) {
  if (v == 0.0) return;  // proto3 default skip
  out->push_back(static_cast<char>((field << 3) | 1));  // wire type 1
  uint64_t bits;
  std::memcpy(&bits, &v, 8);
  for (int i = 0; i < 8; ++i)
    out->push_back(static_cast<char>((bits >> (8 * i)) & 0xFF));
}

inline int f64_field_size(double v) { return v == 0.0 ? 0 : 9; }

}  // namespace

// meta_blob: per emitted row "name \x1f tag \x1f tag ...", rows joined
// with \x1e — one record per row where emit[row] != 0, in row order.
// The bytes are returned via a thread-local buffer: valid until the
// calling thread's next call, no ctx state touched (the flush thread
// encodes while readers keep committing). Returns the byte length, or
// -1 on malformed meta.
long long vn_encode_histo_batch(
    const char* meta_blob, long long meta_len,
    const signed char* kinds, const signed char* scopes,
    const unsigned char* emit, const float* means, const float* weights,
    int rows, int cap, const double* dmin, const double* dmax,
    const double* drecip, double compression, const char** out_ptr) {
  thread_local std::string buf;
  std::string& out = buf;
  out.clear();
  // rough reserve: 8 bytes/centroid + 64/row metadata
  out.reserve(static_cast<size_t>(rows) * 96);

  std::string_view meta(meta_blob, static_cast<size_t>(meta_len));
  size_t mpos = 0;
  const int comp_size = f64_field_size(compression);
  std::vector<std::string_view> tags;
  for (int r = 0; r < rows; ++r) {
    if (!emit[r]) continue;
    if (mpos > meta.size()) return -1;
    size_t rec_end = meta.find('\x1e', mpos);
    if (rec_end == std::string_view::npos) rec_end = meta.size();
    std::string_view rec = meta.substr(mpos, rec_end - mpos);
    mpos = rec_end + 1;

    // split rec into name + tags
    size_t nend = rec.find('\x1f');
    std::string_view name =
        nend == std::string_view::npos ? rec : rec.substr(0, nend);
    tags.clear();
    if (nend != std::string_view::npos) {
      std::string_view rest = rec.substr(nend + 1);
      for (;;) {
        size_t tend = rest.find('\x1f');
        if (tend == std::string_view::npos) {
          tags.push_back(rest);
          break;
        }
        tags.push_back(rest.substr(0, tend));
        rest = rest.substr(tend + 1);
      }
    }

    // count nonzero centroids
    const float* wrow = weights + static_cast<size_t>(r) * cap;
    const float* mrow = means + static_cast<size_t>(r) * cap;
    int n = 0;
    for (int j = 0; j < cap; ++j)
      if (wrow[j] > 0.0f) ++n;

    // --- sizes, innermost out ---
    int centroids_size = 0;
    if (n > 0) {
      int packed = 4 * n;
      centroids_size = 2 * (1 + varint_size(packed) + packed);
    }
    int digest_size = 0;
    if (centroids_size > 0)
      digest_size += 1 + varint_size(centroids_size) + centroids_size;
    digest_size += f64_field_size(dmin[r]) + f64_field_size(dmax[r]) +
                   f64_field_size(drecip[r]) + comp_size;

    int metric_size = 0;
    if (!name.empty())
      metric_size += 1 + varint_size(name.size()) + (int)name.size();
    for (std::string_view tag : tags)
      metric_size += 1 + varint_size(tag.size()) + (int)tag.size();
    if (kinds[r] != 0) metric_size += 1 + varint_size((uint64_t)kinds[r]);
    if (scopes[r] != 0) metric_size += 1 + varint_size((uint64_t)scopes[r]);
    metric_size += 1 + varint_size(digest_size) + digest_size;

    // --- emit ---
    out.push_back('\x0a');  // MetricBatch.metrics, field 1 LEN
    put_varint(&out, metric_size);
    if (!name.empty()) {
      out.push_back('\x0a');  // name field 1
      put_varint(&out, name.size());
      out.append(name);
    }
    for (std::string_view tag : tags) {
      out.push_back('\x12');  // tags field 2
      put_varint(&out, tag.size());
      out.append(tag);
    }
    if (kinds[r] != 0) {
      out.push_back('\x18');  // kind field 3
      put_varint(&out, (uint64_t)kinds[r]);
    }
    if (scopes[r] != 0) {
      out.push_back('\x20');  // scope field 4
      put_varint(&out, (uint64_t)scopes[r]);
    }
    out.push_back('\x3a');  // digest field 7
    put_varint(&out, digest_size);
    if (centroids_size > 0) {
      out.push_back('\x0a');  // centroids field 1
      put_varint(&out, centroids_size);
      int packed = 4 * n;
      out.push_back('\x0a');  // means field 1, packed
      put_varint(&out, packed);
      for (int j = 0; j < cap; ++j) {
        if (wrow[j] > 0.0f) {
          uint32_t bits;
          std::memcpy(&bits, &mrow[j], 4);
          out.push_back(static_cast<char>(bits & 0xFF));
          out.push_back(static_cast<char>((bits >> 8) & 0xFF));
          out.push_back(static_cast<char>((bits >> 16) & 0xFF));
          out.push_back(static_cast<char>((bits >> 24) & 0xFF));
        }
      }
      out.push_back('\x12');  // weights field 2, packed
      put_varint(&out, packed);
      for (int j = 0; j < cap; ++j) {
        if (wrow[j] > 0.0f) {
          uint32_t bits;
          std::memcpy(&bits, &wrow[j], 4);
          out.push_back(static_cast<char>(bits & 0xFF));
          out.push_back(static_cast<char>((bits >> 8) & 0xFF));
          out.push_back(static_cast<char>((bits >> 16) & 0xFF));
          out.push_back(static_cast<char>((bits >> 24) & 0xFF));
        }
      }
    }
    put_f64(&out, 2, dmin[r]);
    put_f64(&out, 3, dmax[r]);
    put_f64(&out, 4, drecip[r]);
    put_f64(&out, 5, compression);
  }
  *out_ptr = out.data();
  return static_cast<long long>(out.size());
}

void vn_ctx_reset(void* p) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  ctx->dir.reset();
  ctx->next_histo_row = ctx->next_set_row = 0;
  ctx->next_counter_row = ctx->next_gauge_row = 0;
  // drop the staging plane wholesale: rows re-register next epoch and a
  // fresh plane comes back zeroed (slot validity is gated on wts > 0, so
  // stale values must never survive a reset)
  delete ctx->stage;
  ctx->stage = nullptr;
  ctx->h_rows.clear();
  ctx->h_vals.clear();
  ctx->h_wts.clear();
  ctx->c_rows.clear();
  ctx->c_contribs.clear();
  ctx->g_rows.clear();
  ctx->g_vals.clear();
  ctx->g_last.clear();
  ctx->s_rows.clear();
  ctx->s_idx.clear();
  ctx->s_rank.clear();
  ctx->new_series.clear();
  ctx->other_lines.clear();
  ctx->processed = 0;
  ctx->errors = 0;
  ctx->overload_dropped = 0;
  ctx->ssf_spans = 0;
  ctx->ssf_invalid = 0;
  ctx->ssf_services.clear();
  ctx->ssf_services_out.clear();
  ctx->ssf_fallback.clear();
  ctx->ssf_fallback_bytes = 0;
}

// Ingest a datagram (possibly multiple newline-separated lines).
// Returns the number of metric lines accepted.
int vn_ingest(void* p, const char* buf, int len) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  std::string_view data(buf, static_cast<size_t>(len));
  int accepted = 0;
  while (!data.empty()) {
    size_t nl = data.find('\n');
    std::string_view line =
        nl == std::string_view::npos ? data : data.substr(0, nl);
    data = nl == std::string_view::npos ? std::string_view()
                                        : data.substr(nl + 1);
    if (line.empty()) continue;
    if (line.substr(0, 3) == "_e{" || line.substr(0, 3) == "_sc") {
      ctx->other_lines.append(line);
      ctx->other_lines.push_back('\n');
      continue;
    }
    if (handle_line(ctx, line)) {
      ++ctx->processed;
      ++accepted;
    } else {
      ++ctx->errors;
    }
  }
  return accepted;
}

// Sharded ingest: parse each line lock-free (thread-local scratch), then
// commit into shard digest % nctx under only that shard's mutex — the
// native twin of the reference's contention-free Digest%N worker routing
// (server.go:1028-1039). Multiple SO_REUSEPORT readers call this
// concurrently; ctypes drops the GIL, so parsing genuinely parallelizes.
// Events/service checks and parse errors land on the caller's home shard
// so one noisy event stream can't serialize every reader behind shard 0.
// With nctx == 1 and home == 0 this degenerates to the shared-nothing
// per-reader commit path: every line commits into the caller's own ctx
// under a mutex nobody else touches on the line path.
int vn_ingest_home(void** ctxps, int nctx, const char* buf, int len,
                   int home) {
  thread_local Scratch sc;
  Ctx** ctxs = reinterpret_cast<Ctx**>(ctxps);
  std::string_view data(buf, static_cast<size_t>(len));
  int accepted = 0;
  while (!data.empty()) {
    size_t nl = data.find('\n');
    std::string_view line =
        nl == std::string_view::npos ? data : data.substr(0, nl);
    data = nl == std::string_view::npos ? std::string_view()
                                        : data.substr(nl + 1);
    if (line.empty()) continue;
    if (line.substr(0, 3) == "_e{" || line.substr(0, 3) == "_sc") {
      std::lock_guard<std::recursive_mutex> g(ctxs[home]->mu);
      ctxs[home]->other_lines.append(line);
      ctxs[home]->other_lines.push_back('\n');
      continue;
    }
    Parsed parsed;
    if (!parse_line(&sc, line, &parsed)) {
      std::lock_guard<std::recursive_mutex> g(ctxs[home]->mu);
      ++ctxs[home]->errors;
      continue;
    }
    Ctx* target = ctxs[parsed.digest % static_cast<uint32_t>(nctx)];
    if (!g_lock_stats.load(std::memory_order_relaxed)) {
      std::lock_guard<std::recursive_mutex> g(target->mu);
      if (commit_metric(target, parsed, sc.joined)) {
        ++target->processed;
        ++accepted;
      } else {
        ++target->errors;
      }
      continue;
    }
    // instrumented commit: wait time (blocked acquire) and hold time of
    // this shard's mutex, with sample rings for percentiles
    int64_t t0 = now_ns();
    bool contended = !target->mu.try_lock();
    if (contended) target->mu.lock();
    int64_t t1 = now_ns();
    if (commit_metric(target, parsed, sc.joined)) {
      ++target->processed;
      ++accepted;
    } else {
      ++target->errors;
    }
    int64_t t2 = now_ns();
    ++target->lk_acquisitions;
    if (contended) ++target->lk_contended;
    int64_t wait = contended ? (t1 - t0) : 0;
    target->lk_wait_ns_total += wait;
    target->lk_hold_ns_total += t2 - t1;
    int slot = target->lk_ring_n % Ctx::kLockRing;
    target->lk_wait_ring[slot] = wait;
    target->lk_hold_ring[slot] = t2 - t1;
    ++target->lk_ring_n;
    target->mu.unlock();
  }
  return accepted;
}

int vn_ingest_routed(void** ctxps, int nctx, const char* buf, int len) {
  return vn_ingest_home(ctxps, nctx, buf, len, 0);
}

// ---------------------------------------------------------------------------
// Native UDP reader: a C++ thread owning the recv loop — datagram to
// staged sample with no Python (and no GIL) anywhere on the path. The
// Python reference loop is Server._read_metric_socket (the reference's
// ReadMetricSocket, server.go:1123); this replaces it when
// tpu_native_readers is on. Stop leaves the fd OPEN so queued datagrams
// survive an fd-handoff restart, mirroring the quiesce semantics.

namespace {

struct Reader {
  std::thread th;
  std::atomic<bool> stop{false};
  std::atomic<long long> packets{0};
  int fd = -1;
  int max_len = 0;
  int home = 0;  // shard receiving this reader's events/errors
  std::vector<Ctx*> ctxs;
};

void reader_loop(Reader* r) {
  std::vector<char> buf(static_cast<size_t>(r->max_len) + 1);
  while (!r->stop.load(std::memory_order_acquire)) {
    ssize_t n = recv(r->fd, buf.data(), buf.size(), 0);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
        continue;  // SO_RCVTIMEO tick: poll the stop flag
      break;  // fd closed under us (shutdown)
    }
    r->packets.fetch_add(1, std::memory_order_relaxed);
    if (n > r->max_len) {
      std::lock_guard<std::recursive_mutex> g(r->ctxs[r->home]->mu);
      ++r->ctxs[r->home]->errors;
      continue;
    }
    vn_ingest_home(reinterpret_cast<void**>(r->ctxs.data()),
                   static_cast<int>(r->ctxs.size()), buf.data(),
                   static_cast<int>(n), r->home);
  }
}

// SSF datagram reader: one unframed span per datagram, decoded +
// span->metric extracted in C++. Spans carrying STATUS samples buffer
// raw for the Python fallback (drained by the pump / epoch close).
struct SsfReader {
  std::thread th;
  std::atomic<bool> stop{false};
  std::atomic<long long> packets{0};
  int fd = -1;
  int max_len = 0;
  Ctx* ctx = nullptr;
  std::string ind, obj;
  double uniq_rate = 0.0;
};

void ssf_reader_loop(SsfReader* r) {
  std::vector<char> buf(static_cast<size_t>(r->max_len) + 1);
  while (!r->stop.load(std::memory_order_acquire)) {
    ssize_t n = recv(r->fd, buf.data(), buf.size(), 0);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
        continue;
      break;
    }
    r->packets.fetch_add(1, std::memory_order_relaxed);
    std::lock_guard<std::recursive_mutex> g(r->ctx->mu);
    if (n == 0 || n > r->max_len) {
      ++r->ctx->errors;
      continue;
    }
    int rc = ingest_ssf_span(r->ctx, std::string_view(buf.data(), n),
                             r->ind, r->obj, r->uniq_rate);
    if (rc == 1) {
      // one accepted span = one processed unit, matching the Python
      // path's worker.ingest_ssf_packet accounting
      ++r->ctx->processed;
    } else if (rc == 0) {
      ++r->ctx->errors;
    } else if (rc == -1) {
      Ctx* c = r->ctx;
      if (c->ssf_fallback_bytes + n > Ctx::kSsfFallbackCap) {
        ++c->ssf_invalid;  // fallback buffer full: drop, visibly
      } else {
        c->ssf_fallback.emplace_back(buf.data(), n);
        c->ssf_fallback_bytes += n;
      }
    }
  }
}

}  // namespace

// Start a reader thread on an already-bound datagram fd. The fd is
// switched to blocking with a 500ms SO_RCVTIMEO so the stop flag is
// polled; ownership of the fd stays with the caller. Returns NULL if
// the timeout cannot be applied — a reader whose recv never times out
// could not be stopped, and would hang shutdown/handoff in join().
// home selects the shard that absorbs this reader's events/service
// checks and parse errors (vn_reader_start pins it to 0 for ABI
// compatibility). A reader given nctx == 1 owns its ctx outright: the
// shared-nothing per-reader commit shape.
void* vn_reader_start2(void** ctxps, int nctx, int fd, int max_len,
                       int home) {
  if (home < 0 || home >= nctx) return nullptr;
  int fl = fcntl(fd, F_GETFL);
  if (fl < 0) return nullptr;
  if ((fl & O_NONBLOCK) && fcntl(fd, F_SETFL, fl & ~O_NONBLOCK) < 0)
    return nullptr;
  struct timeval tv;
  tv.tv_sec = 0;
  tv.tv_usec = 500000;
  if (setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv) != 0)
    return nullptr;
  Reader* r = new Reader();
  r->fd = fd;
  r->max_len = max_len;
  r->home = home;
  for (int i = 0; i < nctx; ++i)
    r->ctxs.push_back(static_cast<Ctx*>(ctxps[i]));
  r->th = std::thread(reader_loop, r);
  return r;
}

void* vn_reader_start(void** ctxps, int nctx, int fd, int max_len) {
  return vn_reader_start2(ctxps, nctx, fd, max_len, 0);
}

long long vn_reader_packets(void* p) {
  return static_cast<Reader*>(p)->packets.load(std::memory_order_relaxed);
}

// Stop and join the reader, then free it. Does NOT close the fd.
// Returns the FINAL packet count, read after the join — the thread
// keeps ingesting for up to one SO_RCVTIMEO tick after the stop flag
// is set, and a count snapshotted before the join would lose those.
long long vn_reader_stop(void* p) {
  Reader* r = static_cast<Reader*>(p);
  r->stop.store(true, std::memory_order_release);
  if (r->th.joinable()) r->th.join();
  long long final_count = r->packets.load(std::memory_order_relaxed);
  delete r;
  return final_count;
}

// Line-delimited TCP stream reader: one C++ thread per plain (non-TLS)
// statsd connection. Reassembles newline-split lines across reads and
// routes them like the datagram readers; an overlong partial line is
// dropped (counted) and the reader skips to the next newline. The
// reader OWNS the fd and closes it on exit — the Python side dup()s the
// accepted socket before handing it over.
void* vn_stream_reader_start(void** ctxps, int nctx, int fd, int max_len);
long long vn_stream_reader_stop(void* p);

namespace {

struct StreamReader {
  std::thread th;
  std::atomic<bool> stop{false};
  std::atomic<bool> finished{false};  // loop exited (peer closed/error)
  std::atomic<long long> lines{0};
  int fd = -1;
  int max_len = 0;
  int home = 0;  // shard receiving this reader's events/errors
  std::vector<Ctx*> ctxs;
};

void stream_reader_loop(StreamReader* r) {
  std::vector<char> chunk(64 << 10);
  std::string buf;
  bool skipping = false;  // inside an overlong line, waiting for \n
  while (!r->stop.load(std::memory_order_acquire)) {
    ssize_t n = recv(r->fd, chunk.data(), chunk.size(), 0);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
        continue;  // SO_RCVTIMEO tick: poll the stop flag
      break;
    }
    if (n == 0) break;  // peer closed
    buf.append(chunk.data(), static_cast<size_t>(n));
    size_t start = 0, nl;
    while ((nl = buf.find('\n', start)) != std::string::npos) {
      size_t len = nl - start;
      if (skipping) {
        skipping = false;  // tail of the dropped overlong line
      } else if (len > 0) {
        if (len > static_cast<size_t>(r->max_len)) {
          std::lock_guard<std::recursive_mutex> g(r->ctxs[r->home]->mu);
          ++r->ctxs[r->home]->errors;
        } else {
          vn_ingest_home(reinterpret_cast<void**>(r->ctxs.data()),
                         static_cast<int>(r->ctxs.size()),
                         buf.data() + start, static_cast<int>(len),
                         r->home);
          r->lines.fetch_add(1, std::memory_order_relaxed);
        }
      }
      start = nl + 1;
    }
    buf.erase(0, start);
    if (!skipping && buf.size() > static_cast<size_t>(r->max_len)) {
      // partial line already too long: drop it now (bounded memory;
      // the Python path buffers unboundedly here)
      std::lock_guard<std::recursive_mutex> g(r->ctxs[r->home]->mu);
      ++r->ctxs[r->home]->errors;
      buf.clear();
      skipping = true;
    }
  }
  close(r->fd);
  r->finished.store(true, std::memory_order_release);
}

}  // namespace

// True once the reader's loop exited (peer closed / error): the handle
// should be reaped with vn_stream_reader_stop — an unjoined dead thread
// pins its stack for the process lifetime.
int vn_stream_reader_done(void* p) {
  return static_cast<StreamReader*>(p)->finished.load(
             std::memory_order_acquire)
             ? 1
             : 0;
}

void* vn_stream_reader_start2(void** ctxps, int nctx, int fd, int max_len,
                              int home) {
  if (home < 0 || home >= nctx) return nullptr;
  int fl = fcntl(fd, F_GETFL);
  if (fl < 0) return nullptr;
  if ((fl & O_NONBLOCK) && fcntl(fd, F_SETFL, fl & ~O_NONBLOCK) < 0)
    return nullptr;
  struct timeval tv;
  tv.tv_sec = 0;
  tv.tv_usec = 500000;
  if (setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv) != 0)
    return nullptr;
  StreamReader* r = new StreamReader();
  r->fd = fd;
  r->max_len = max_len;
  r->home = home;
  for (int i = 0; i < nctx; ++i)
    r->ctxs.push_back(static_cast<Ctx*>(ctxps[i]));
  r->th = std::thread(stream_reader_loop, r);
  return r;
}

void* vn_stream_reader_start(void** ctxps, int nctx, int fd, int max_len) {
  return vn_stream_reader_start2(ctxps, nctx, fd, max_len, 0);
}

// Join and free; returns lines ingested. The reader closes its fd.
long long vn_stream_reader_stop(void* p) {
  StreamReader* r = static_cast<StreamReader*>(p);
  r->stop.store(true, std::memory_order_release);
  if (r->th.joinable()) r->th.join();
  long long total = r->lines.load(std::memory_order_relaxed);
  delete r;
  return total;
}

// SSF variant of vn_reader_start: one unframed span per datagram on the
// fd, decoded and extracted in C++; STATUS spans buffer for the Python
// fallback (vn_drain_ssf_fallback). Same stop/timeout contract.
void* vn_ssf_reader_start(void* ctxp, int fd, int max_len,
                          const char* ind, int ind_len, const char* obj,
                          int obj_len, double uniq_rate) {
  int fl = fcntl(fd, F_GETFL);
  if (fl < 0) return nullptr;
  if ((fl & O_NONBLOCK) && fcntl(fd, F_SETFL, fl & ~O_NONBLOCK) < 0)
    return nullptr;
  struct timeval tv;
  tv.tv_sec = 0;
  tv.tv_usec = 500000;
  if (setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv) != 0)
    return nullptr;
  SsfReader* r = new SsfReader();
  r->fd = fd;
  r->max_len = max_len;
  r->ctx = static_cast<Ctx*>(ctxp);
  r->ind.assign(ind, static_cast<size_t>(ind_len));
  r->obj.assign(obj, static_cast<size_t>(obj_len));
  r->uniq_rate = uniq_rate;
  r->th = std::thread(ssf_reader_loop, r);
  return r;
}

long long vn_ssf_reader_stop(void* p) {
  SsfReader* r = static_cast<SsfReader*>(p);
  r->stop.store(true, std::memory_order_release);
  if (r->th.joinable()) r->th.join();
  long long final_count = r->packets.load(std::memory_order_relaxed);
  delete r;
  return final_count;
}

// Drain buffered Python-fallback SSF payloads as [u32 LE len][bytes]
// frames. Only whole frames are written; leftovers stay buffered.
int vn_drain_ssf_fallback(void* p, char* buf, int cap) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  int written = 0;
  size_t taken = 0;
  for (const std::string& pkt : ctx->ssf_fallback) {
    size_t need = 4 + pkt.size();
    if (cap < 0 || static_cast<size_t>(cap) - written < need) break;
    uint32_t len32 = static_cast<uint32_t>(pkt.size());
    std::memcpy(buf + written, &len32, 4);
    std::memcpy(buf + written + 4, pkt.data(), pkt.size());
    written += static_cast<int>(need);
    ++taken;
  }
  if (taken) {
    for (size_t i = 0; i < taken; ++i)
      ctx->ssf_fallback_bytes -= ctx->ssf_fallback[i].size();
    ctx->ssf_fallback.erase(ctx->ssf_fallback.begin(),
                            ctx->ssf_fallback.begin() + taken);
  }
  return written;
}

// Enable/disable commit-path lock timing (global; affects all contexts).
void vn_set_lock_stats(int enabled) {
  g_lock_stats.store(enabled != 0, std::memory_order_relaxed);
}

// Totals: [acquisitions, contended, wait_ns_total, hold_ns_total,
// ring_samples]. Ring samples (most recent min(ring_samples, 4096)
// waits/holds, ns) land in wait_out/hold_out when non-null; returns the
// number of ring entries written.
int vn_lock_stats(void* p, long long out[5], long long* wait_out,
                  long long* hold_out, int cap) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> g(ctx->mu);
  out[0] = ctx->lk_acquisitions;
  out[1] = ctx->lk_contended;
  out[2] = ctx->lk_wait_ns_total;
  out[3] = ctx->lk_hold_ns_total;
  int n = static_cast<int>(
      std::min<int64_t>(ctx->lk_ring_n, Ctx::kLockRing));
  out[4] = n;
  int wrote = 0;
  if (wait_out != nullptr && hold_out != nullptr) {
    wrote = std::min(n, cap);
    for (int i = 0; i < wrote; ++i) {
      wait_out[i] = ctx->lk_wait_ring[i];
      hold_out[i] = ctx->lk_hold_ring[i];
    }
  }
  return wrote;
}

void vn_lock_stats_reset(void* p) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> g(ctx->mu);
  ctx->lk_acquisitions = 0;
  ctx->lk_contended = 0;
  ctx->lk_wait_ns_total = 0;
  ctx->lk_hold_ns_total = 0;
  ctx->lk_ring_n = 0;
}

static int locked_size(void* p, const std::vector<int32_t> Ctx::* field) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> g(ctx->mu);
  return static_cast<int>((ctx->*field).size());
}

static int locked_i32(void* p, int32_t Ctx::* field) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> g(ctx->mu);
  return ctx->*field;
}

int vn_pending_histo(void* p) { return locked_size(p, &Ctx::h_rows); }
int vn_pending_set(void* p) { return locked_size(p, &Ctx::s_rows); }
int vn_pending_counter(void* p) { return locked_size(p, &Ctx::c_rows); }
int vn_pending_gauge(void* p) { return locked_size(p, &Ctx::g_rows); }
int vn_num_histo_rows(void* p) { return locked_i32(p, &Ctx::next_histo_row); }
int vn_num_set_rows(void* p) { return locked_i32(p, &Ctx::next_set_row); }
int vn_num_counter_rows(void* p) {
  return locked_i32(p, &Ctx::next_counter_row);
}
int vn_num_gauge_rows(void* p) { return locked_i32(p, &Ctx::next_gauge_row); }
long long vn_processed(void* p) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> g(ctx->mu);
  return ctx->processed;
}
long long vn_errors(void* p) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> g(ctx->mu);
  return ctx->errors;
}

long long vn_overload_dropped(void* p) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> g(ctx->mu);
  return ctx->overload_dropped;
}

void vn_set_spill_cap(void* p, long long cap) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> g(ctx->mu);
  if (cap > 0) ctx->spill_cap = static_cast<size_t>(cap);
  // A raised cap lets g_rows resume push_back, so the onset-built
  // last-write index no longer covers the batch tail; clear it so the
  // next overload onset rebuilds it over the full batch (a stale entry
  // would update an older-positioned duplicate, losing LWW at drain).
  ctx->g_last.clear();
}

int vn_drain_histo(void* p, int32_t* rows, float* vals, float* wts, int cap) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  int n = std::min<int>(cap, static_cast<int>(ctx->h_rows.size()));
  std::memcpy(rows, ctx->h_rows.data(), n * sizeof(int32_t));
  std::memcpy(vals, ctx->h_vals.data(), n * sizeof(float));
  std::memcpy(wts, ctx->h_wts.data(), n * sizeof(float));
  ctx->h_rows.erase(ctx->h_rows.begin(), ctx->h_rows.begin() + n);
  ctx->h_vals.erase(ctx->h_vals.begin(), ctx->h_vals.begin() + n);
  ctx->h_wts.erase(ctx->h_wts.begin(), ctx->h_wts.begin() + n);
  return n;
}

int vn_drain_set(void* p, int32_t* rows, int32_t* idx, int8_t* rank,
                 int cap) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  int n = std::min<int>(cap, static_cast<int>(ctx->s_rows.size()));
  std::memcpy(rows, ctx->s_rows.data(), n * sizeof(int32_t));
  std::memcpy(idx, ctx->s_idx.data(), n * sizeof(int32_t));
  std::memcpy(rank, ctx->s_rank.data(), n * sizeof(int8_t));
  ctx->s_rows.erase(ctx->s_rows.begin(), ctx->s_rows.begin() + n);
  ctx->s_idx.erase(ctx->s_idx.begin(), ctx->s_idx.begin() + n);
  ctx->s_rank.erase(ctx->s_rank.begin(), ctx->s_rank.begin() + n);
  return n;
}

int vn_drain_counter(void* p, int32_t* rows, double* contribs, int cap) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  int n = std::min<int>(cap, static_cast<int>(ctx->c_rows.size()));
  std::memcpy(rows, ctx->c_rows.data(), n * sizeof(int32_t));
  std::memcpy(contribs, ctx->c_contribs.data(), n * sizeof(double));
  ctx->c_rows.erase(ctx->c_rows.begin(), ctx->c_rows.begin() + n);
  ctx->c_contribs.erase(ctx->c_contribs.begin(),
                        ctx->c_contribs.begin() + n);
  return n;
}

int vn_drain_gauge(void* p, int32_t* rows, double* vals, int cap) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  int n = std::min<int>(cap, static_cast<int>(ctx->g_rows.size()));
  std::memcpy(rows, ctx->g_rows.data(), n * sizeof(int32_t));
  std::memcpy(vals, ctx->g_vals.data(), n * sizeof(double));
  ctx->g_rows.erase(ctx->g_rows.begin(), ctx->g_rows.begin() + n);
  ctx->g_vals.erase(ctx->g_vals.begin(), ctx->g_vals.begin() + n);
  ctx->g_last.clear();  // indices into the batch are invalid after erase
  return n;
}

// Cheap emptiness probe so Python-side upsert loops (the global tier
// imports one series at a time) can skip the buffer-allocating drain
// when nothing is pending.
int vn_pending_new_series(void* p) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  return static_cast<int>(ctx->new_series.size());
}

// Drain new-series records: fills parallel arrays plus a packed string
// buffer of "name\x1fjoined_tags\x1e" records. Returns the count drained
// (0 if strbuf is too small for the next record).
int vn_drain_new_series(void* p, int32_t* pools, int32_t* rows,
                        int32_t* kinds, int32_t* scopes, char* strbuf,
                        int strcap, int* strlen_out, int max) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  int n = 0;
  int off = 0;
  while (n < max && n < static_cast<int>(ctx->new_series.size())) {
    const NewSeries& ns = ctx->new_series[n];
    int need = static_cast<int>(ns.name.size() + ns.joined_tags.size() + 2);
    if (off + need > strcap) break;
    pools[n] = ns.pool;
    rows[n] = ns.row;
    kinds[n] = ns.kind;
    scopes[n] = ns.scope_class;
    std::memcpy(strbuf + off, ns.name.data(), ns.name.size());
    off += static_cast<int>(ns.name.size());
    strbuf[off++] = '\x1f';
    std::memcpy(strbuf + off, ns.joined_tags.data(), ns.joined_tags.size());
    off += static_cast<int>(ns.joined_tags.size());
    strbuf[off++] = '\x1e';
    ++n;
  }
  ctx->new_series.erase(ctx->new_series.begin(),
                        ctx->new_series.begin() + n);
  *strlen_out = off;
  return n;
}

// Directory upsert for the Python-side ingest paths (SSF-derived metrics,
// imports): returns the row id, assigning a new one when the series is
// unseen this epoch. kind: MetricKind; scope_class: ScopeClass. The new
// series is recorded for vn_drain_new_series like any parsed one.
int vn_upsert(void* p, const char* name, int name_len, int kind,
              const char* joined_tags, int tags_len, int scope_class) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  std::string_view name_sv(name, static_cast<size_t>(name_len));
  std::string_view tags_sv(joined_tags, static_cast<size_t>(tags_len));
  MetricKind k = static_cast<MetricKind>(kind);
  const char* type_str = kind_type_string(k);

  uint32_t digest = fnv1a32(name_sv);
  digest = fnv1a32(type_str, digest);
  digest = fnv1a32(tags_sv, digest);
  uint64_t key_hash =
      dir_key_hash(digest, name_sv, type_str, tags_sv, scope_class);

  int32_t* next = nullptr;
  int32_t pool = 0;
  switch (k) {
    case KIND_HISTOGRAM:
    case KIND_TIMER:
      next = &ctx->next_histo_row;
      pool = 0;
      break;
    case KIND_SET:
      next = &ctx->next_set_row;
      pool = 1;
      break;
    case KIND_COUNTER:
      next = &ctx->next_counter_row;
      pool = 2;
      break;
    case KIND_GAUGE:
      next = &ctx->next_gauge_row;
      pool = 3;
      break;
  }
  bool created = false;
  int32_t row = ctx->dir.upsert_parts(
      key_hash, name_sv, type_str, tags_sv,
      static_cast<char>('0' + scope_class), *next, &created);
  if (created) {
    ++*next;
    NewSeries ns;
    ns.pool = pool;
    ns.row = row;
    ns.kind = kind;
    ns.scope_class = scope_class;
    ns.name.assign(name_sv);
    ns.joined_tags.assign(tags_sv);
    ctx->new_series.push_back(std::move(ns));
  }
  return row;
}

// ---------------------------------------------------------------------------
// Forward-batch wire decoder + batched directory upsert: the import
// side of the native forward path. A global veneur receiving 1M
// forwarded digests spent ~50s/flush building Python protobuf objects
// and upserting per metric; the decoder parses the MetricBatch wire
// into SoA buffers (one C call), and vn_upsert_many assigns directory
// rows for a whole chunk under one lock hold.

namespace {

struct Decoded {
  std::string meta;  // per metric: name \x1f joined_tags, recs \x1e-joined
  std::vector<uint8_t> kinds;       // pb MetricKind enum (== native kinds)
  std::vector<uint8_t> scopes;      // pb Scope enum (== ScopeClass)
  std::vector<uint8_t> value_kind;  // 0 none, 1 counter, 2 gauge,
                                    // 3 digest, 4 hll
  std::vector<uint32_t> digests;    // worker-routing digest
  std::vector<double> scalars;      // counter/gauge value
  std::vector<double> dmin, dmax, drecip, compression;
  std::vector<long long> cent_off;  // [n+1]
  std::vector<float> cent_means, cent_weights;
  std::vector<long long> hll_off;  // [n+1]
  std::string hll_bytes;
  std::vector<int32_t> hll_precision;
  // byte range of each metric's length-prefixed record in the source
  // buffer (tag byte through body end): lets a proxy ring-split a batch
  // by slicing the original bytes, no re-encode (protobuf repeated
  // records concatenate)
  std::vector<long long> rec_off, rec_len;
  // consistent-ring key hash: fmix64(fnv1a64(name + type + joined)) —
  // the proxy's per-metric placement hash, computed here so the Python
  // tier never hashes per metric (distributed/ring.owners_for_hashes)
  std::vector<uint64_t> ring_hash;

  void clear() {
    meta.clear();
    kinds.clear();
    scopes.clear();
    value_kind.clear();
    digests.clear();
    scalars.clear();
    dmin.clear();
    dmax.clear();
    drecip.clear();
    compression.clear();
    cent_off.assign(1, 0);
    cent_means.clear();
    cent_weights.clear();
    hll_off.assign(1, 0);
    hll_bytes.clear();
    hll_precision.clear();
    rec_off.clear();
    rec_len.clear();
    ring_hash.clear();
  }
};

struct WireCursor {
  const uint8_t* p;
  const uint8_t* end;

  bool varint(uint64_t* out) {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      // 10th byte holds bits 63..69 of which only bit 63 exists in a
      // uint64: any higher bit (or a continuation bit demanding an
      // 11th byte) is an overflow every spec parser rejects — silently
      // truncating here made the decoder accept what peers refuse
      if (shift == 63 && (b & 0xFE)) return false;
      v |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) {
        *out = v;
        return true;
      }
      shift += 7;
    }
    return false;
  }

  // TAG varints cap at 5 bytes (uint32 wire grammar); see
  // ProtoReader::tag_varint
  bool tag_varint(uint64_t* out) {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 35) {
      uint8_t b = *p++;
      if (shift == 28 && (b & 0xF0)) return false;
      v |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) {
        *out = v;
        return true;
      }
      shift += 7;
    }
    return false;
  }

  bool skip(uint32_t wire_type) {
    uint64_t tmp;
    switch (wire_type) {
      case 0:
        return varint(&tmp);
      case 1:
        if (end - p < 8) return false;
        p += 8;
        return true;
      case 2: {
        if (!varint(&tmp) || tmp > static_cast<uint64_t>(end - p))
          return false;
        p += tmp;
        return true;
      }
      case 5:
        if (end - p < 4) return false;
        p += 4;
        return true;
      default:
        return false;  // groups unsupported
    }
  }

  bool len_view(std::string_view* out) {
    uint64_t n;
    if (!varint(&n) || n > static_cast<uint64_t>(end - p)) return false;
    *out = std::string_view(reinterpret_cast<const char*>(p),
                            static_cast<size_t>(n));
    p += n;
    return true;
  }

  bool f64(double* out) {
    if (end - p < 8) return false;
    std::memcpy(out, p, 8);
    p += 8;
    return true;
  }
};

bool decode_packed_floats(std::string_view payload, std::vector<float>* out) {
  if (payload.size() % 4 != 0) return false;
  size_t n = payload.size() / 4;
  size_t base = out->size();
  out->resize(base + n);
  std::memcpy(out->data() + base, payload.data(), payload.size());
  return true;
}

bool decode_centroids(std::string_view body, std::vector<float>* means,
                      std::vector<float>* weights) {
  WireCursor c{reinterpret_cast<const uint8_t*>(body.data()),
               reinterpret_cast<const uint8_t*>(body.data() + body.size())};
  while (c.p < c.end) {
    uint64_t tag;
    if (!c.tag_varint(&tag)) return false;
    uint32_t field = static_cast<uint32_t>(tag >> 3);
    uint32_t wt = static_cast<uint32_t>(tag & 7);
    if (field == 0) return false;  // protobuf forbids field number 0
    if (field == 1 || field == 2) {
      std::vector<float>* dst = field == 1 ? means : weights;
      if (wt == 2) {  // packed
        std::string_view payload;
        if (!c.len_view(&payload) || !decode_packed_floats(payload, dst))
          return false;
      } else if (wt == 5) {  // unpacked single
        if (c.end - c.p < 4) return false;
        float v;
        std::memcpy(&v, c.p, 4);
        c.p += 4;
        dst->push_back(v);
      } else {
        return false;
      }
    } else if (!c.skip(wt)) {
      return false;
    }
  }
  return true;
}

void sanitize_seps(std::string* s) {
  for (char& ch : *s)
    if (ch == '\x1e' || ch == '\x1f') ch = '_';
}

// protobuf rejects `string` fields that aren't valid UTF-8; the native
// decoder must agree (strictness parity with the Python fallback —
// pinned by the decoder fuzz test)
// one Metric submessage → appended SoA entry; false on malformed
bool decode_metric(std::string_view body, Decoded* d) {
  WireCursor c{reinterpret_cast<const uint8_t*>(body.data()),
               reinterpret_cast<const uint8_t*>(body.data() + body.size())};
  std::string name;
  std::string joined;
  uint64_t kind = 0, scope = 0;
  uint8_t vkind = 0;
  double scalar = 0, mn = 0, mx = 0, rc = 0, comp = 0;
  size_t cent_means_base = d->cent_means.size();
  size_t cent_w_base = d->cent_weights.size();
  int32_t precision = 0;
  while (c.p < c.end) {
    uint64_t tag;
    if (!c.tag_varint(&tag)) return false;
    uint32_t field = static_cast<uint32_t>(tag >> 3);
    uint32_t wt = static_cast<uint32_t>(tag & 7);
    if (field == 0) return false;  // protobuf forbids field number 0
    switch (field) {
      case 1: {  // name (proto3 string: must be valid UTF-8)
        std::string_view v;
        if (wt != 2 || !c.len_view(&v) || !valid_utf8(v)) return false;
        name.assign(v);
        break;
      }
      case 2: {  // tags (repeated proto3 string)
        std::string_view v;
        if (wt != 2 || !c.len_view(&v) || !valid_utf8(v)) return false;
        if (!joined.empty()) joined.push_back(',');
        joined.append(v);
        break;
      }
      case 3:
        if (wt != 0 || !c.varint(&kind)) return false;
        break;
      case 4:
        if (wt != 0 || !c.varint(&scope)) return false;
        break;
      case 5: {  // counter { sfixed64 value = 1 }
        std::string_view v;
        if (wt != 2 || !c.len_view(&v)) return false;
        vkind = 1;
        WireCursor ic{reinterpret_cast<const uint8_t*>(v.data()),
                      reinterpret_cast<const uint8_t*>(v.data() + v.size())};
        while (ic.p < ic.end) {
          uint64_t it;
          if (!ic.tag_varint(&it)) return false;
          if ((it >> 3) == 0) return false;
          if ((it >> 3) == 1 && (it & 7) == 1) {
            int64_t sv;
            if (ic.end - ic.p < 8) return false;
            std::memcpy(&sv, ic.p, 8);
            ic.p += 8;
            scalar = static_cast<double>(sv);
          } else if (!ic.skip(static_cast<uint32_t>(it & 7))) {
            return false;
          }
        }
        break;
      }
      case 6: {  // gauge { double value = 1 }
        std::string_view v;
        if (wt != 2 || !c.len_view(&v)) return false;
        vkind = 2;
        WireCursor ic{reinterpret_cast<const uint8_t*>(v.data()),
                      reinterpret_cast<const uint8_t*>(v.data() + v.size())};
        while (ic.p < ic.end) {
          uint64_t it;
          if (!ic.tag_varint(&it)) return false;
          if ((it >> 3) == 0) return false;
          if ((it >> 3) == 1 && (it & 7) == 1) {
            if (!ic.f64(&scalar)) return false;
          } else if (!ic.skip(static_cast<uint32_t>(it & 7))) {
            return false;
          }
        }
        break;
      }
      case 7: {  // digest
        std::string_view v;
        if (wt != 2 || !c.len_view(&v)) return false;
        vkind = 3;
        WireCursor ic{reinterpret_cast<const uint8_t*>(v.data()),
                      reinterpret_cast<const uint8_t*>(v.data() + v.size())};
        while (ic.p < ic.end) {
          uint64_t it;
          if (!ic.tag_varint(&it)) return false;
          if ((it >> 3) == 0) return false;
          uint32_t f = static_cast<uint32_t>(it >> 3);
          uint32_t w = static_cast<uint32_t>(it & 7);
          if (f == 1 && w == 2) {
            std::string_view cb;
            if (!ic.len_view(&cb) ||
                !decode_centroids(cb, &d->cent_means, &d->cent_weights))
              return false;
          } else if (f >= 2 && f <= 5 && w == 1) {
            double dv;
            if (!ic.f64(&dv)) return false;
            if (f == 2) mn = dv;
            else if (f == 3) mx = dv;
            else if (f == 4) rc = dv;
            else comp = dv;
          } else if (!ic.skip(w)) {
            return false;
          }
        }
        break;
      }
      case 8: {  // hll
        std::string_view v;
        if (wt != 2 || !c.len_view(&v)) return false;
        vkind = 4;
        WireCursor ic{reinterpret_cast<const uint8_t*>(v.data()),
                      reinterpret_cast<const uint8_t*>(v.data() + v.size())};
        while (ic.p < ic.end) {
          uint64_t it;
          if (!ic.tag_varint(&it)) return false;
          if ((it >> 3) == 0) return false;
          uint32_t f = static_cast<uint32_t>(it >> 3);
          uint32_t w = static_cast<uint32_t>(it & 7);
          if (f == 1 && w == 2) {
            std::string_view rb;
            if (!ic.len_view(&rb)) return false;
            d->hll_bytes.append(rb);
          } else if (f == 2 && w == 0) {
            uint64_t pv;
            if (!ic.varint(&pv)) return false;
            precision = static_cast<int32_t>(pv);
          } else if (!ic.skip(w)) {
            return false;
          }
        }
        break;
      }
      default:
        if (!c.skip(wt)) return false;
    }
  }
  if (kind > 4 || scope > 2) return false;
  // centroid means/weights must pair up
  if (d->cent_means.size() - cent_means_base !=
      d->cent_weights.size() - cent_w_base)
    return false;
  sanitize_seps(&name);
  sanitize_seps(&joined);
  const char* type_str = kind_type_string(static_cast<MetricKind>(kind));
  uint32_t digest = fnv1a32(name);
  digest = fnv1a32(type_str, digest);
  digest = fnv1a32(joined, digest);
  uint64_t rh = fnv1a64_continue(name, kFnv64Offset);
  rh = fnv1a64_continue(type_str, rh);
  rh = fmix64(fnv1a64_continue(joined, rh));
  d->ring_hash.push_back(rh);

  if (!d->meta.empty()) d->meta.push_back('\x1e');
  d->meta.append(name);
  d->meta.push_back('\x1f');
  d->meta.append(joined);
  d->kinds.push_back(static_cast<uint8_t>(kind));
  d->scopes.push_back(static_cast<uint8_t>(scope));
  d->value_kind.push_back(vkind);
  d->digests.push_back(digest);
  d->scalars.push_back(scalar);
  d->dmin.push_back(mn);
  d->dmax.push_back(mx);
  d->drecip.push_back(rc);
  d->compression.push_back(comp);
  d->cent_off.push_back(static_cast<long long>(d->cent_means.size()));
  d->hll_off.push_back(static_cast<long long>(d->hll_bytes.size()));
  d->hll_precision.push_back(precision);
  return true;
}

thread_local Decoded g_decoded;

}  // namespace

// Decode a serialized veneurtpu.MetricBatch into SoA views. The views
// live in thread-local storage: valid until the calling thread's next
// decode. Returns the metric count, or -1 on malformed input.
long long vn_decode_metric_batch(
    const char* buf, long long len, const char** meta,
    long long* meta_len, const uint8_t** kinds, const uint8_t** scopes,
    const uint8_t** value_kind, const uint32_t** digests,
    const double** scalars, const double** dmin, const double** dmax,
    const double** drecip, const double** compression,
    const long long** cent_off, const float** cent_means,
    const float** cent_weights, const long long** hll_off,
    const char** hll_bytes, const int32_t** hll_precision,
    const long long** rec_off, const long long** rec_len,
    const uint64_t** ring_hash) {
  Decoded& d = g_decoded;
  d.clear();
  WireCursor c{reinterpret_cast<const uint8_t*>(buf),
               reinterpret_cast<const uint8_t*>(buf + len)};
  const uint8_t* base = reinterpret_cast<const uint8_t*>(buf);
  while (c.p < c.end) {
    const uint8_t* tag_start = c.p;
    uint64_t tag;
    if (!c.tag_varint(&tag)) return -1;
    uint32_t field = static_cast<uint32_t>(tag >> 3);
    uint32_t wt = static_cast<uint32_t>(tag & 7);
    if (field == 0) return -1;  // protobuf forbids field number 0
    if (field == 1 && wt == 2) {
      std::string_view body;
      if (!c.len_view(&body) || !decode_metric(body, &d)) return -1;
      d.rec_off.push_back(static_cast<long long>(tag_start - base));
      d.rec_len.push_back(static_cast<long long>(c.p - tag_start));
    } else if (!c.skip(wt)) {
      return -1;
    }
  }
  *meta = d.meta.data();
  *meta_len = static_cast<long long>(d.meta.size());
  *kinds = d.kinds.data();
  *scopes = d.scopes.data();
  *value_kind = d.value_kind.data();
  *digests = d.digests.data();
  *scalars = d.scalars.data();
  *dmin = d.dmin.data();
  *dmax = d.dmax.data();
  *drecip = d.drecip.data();
  *compression = d.compression.data();
  *cent_off = d.cent_off.data();
  *cent_means = d.cent_means.data();
  *cent_weights = d.cent_weights.data();
  *hll_off = d.hll_off.data();
  *hll_bytes = d.hll_bytes.data();
  *hll_precision = d.hll_precision.data();
  *rec_off = d.rec_off.data();
  *rec_len = d.rec_len.data();
  *ring_hash = d.ring_hash.data();
  return static_cast<long long>(d.kinds.size());
}

// Batch directory upsert: one lock hold for a whole import chunk.
// meta is the \x1e/\x1f-framed record blob (one record per metric, in
// order); sel[i] != 0 selects the metrics owned by this context's
// worker; out_rows[i] = assigned row, or -1 where unselected/invalid.
// Returns the number of selected upserts.
long long vn_upsert_many(void* p, const char* meta, long long meta_len,
                         const uint8_t* kinds, const uint8_t* scopes,
                         const uint8_t* sel, long long n,
                         int32_t* out_rows) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  std::string_view blob(meta, static_cast<size_t>(meta_len));
  size_t mpos = 0;
  long long done = 0;
  for (long long i = 0; i < n; ++i) {
    size_t rec_end = blob.find('\x1e', mpos);
    if (rec_end == std::string_view::npos) rec_end = blob.size();
    std::string_view rec = blob.substr(mpos, rec_end - mpos);
    mpos = rec_end + 1;
    if (!sel[i]) {
      out_rows[i] = -1;
      continue;
    }
    size_t nend = rec.find('\x1f');
    std::string_view name =
        nend == std::string_view::npos ? rec : rec.substr(0, nend);
    std::string_view joined =
        nend == std::string_view::npos ? std::string_view()
                                       : rec.substr(nend + 1);
    MetricKind k = static_cast<MetricKind>(kinds[i]);
    const char* type_str = kind_type_string(k);

    uint32_t digest = fnv1a32(name);
    digest = fnv1a32(type_str, digest);
    digest = fnv1a32(joined, digest);
    uint64_t key_hash =
        dir_key_hash(digest, name, type_str, joined, scopes[i]);

    int32_t* next = nullptr;
    int32_t pool = 0;
    switch (k) {
      case KIND_HISTOGRAM:
      case KIND_TIMER:
        next = &ctx->next_histo_row;
        pool = 0;
        break;
      case KIND_SET:
        next = &ctx->next_set_row;
        pool = 1;
        break;
      case KIND_COUNTER:
        next = &ctx->next_counter_row;
        pool = 2;
        break;
      case KIND_GAUGE:
        next = &ctx->next_gauge_row;
        pool = 3;
        break;
    }
    bool created = false;
    int32_t row = ctx->dir.upsert_parts(
        key_hash, name, type_str, joined,
        static_cast<char>('0' + scopes[i]), *next, &created);
    if (created) {
      ++*next;
      NewSeries ns;
      ns.pool = pool;
      ns.row = row;
      ns.kind = static_cast<int>(kinds[i]);
      ns.scope_class = static_cast<int>(scopes[i]);
      ns.name.assign(name);
      ns.joined_tags.assign(joined);
      ctx->new_series.push_back(std::move(ns));
    }
    out_rows[i] = row;
    ++done;
  }
  return done;
}

// ---------------------------------------------------------------------------
// Columnar emit serializers (vn_encode_datadog_series, the statsd line
// emitters, vn_encode_signalfx_body, exposition text, deflate) live in
// emit.cpp — the emit tier of the library (built into the same .so).

// SSF span fast path. Returns 1 ok, 0 decode error, -1 fallback needed
// (span carries STATUS samples; nothing was ingested).
int vn_ingest_ssf(void* p, const char* buf, int len, const char* ind_name,
                  int ind_len, const char* obj_name, int obj_len,
                  double uniq_rate) {
  return ingest_ssf_span(
      static_cast<Ctx*>(p), std::string_view(buf, len),
      std::string_view(ind_name, ind_len), std::string_view(obj_name, obj_len),
      uniq_rate);
}

// Batched SSF ingest: buf holds frames of [u32 LE length][span bytes].
// Returns the number of spans ingested; decode errors are counted in
// *errors_out, spans needing the Python fallback are APPENDED to
// fallback_off/fallback_len (caller-provided arrays of capacity
// fallback_cap; pass 0 to count-as-error instead) as offsets into buf,
// with the appended count written to *nfall_out.
int vn_ingest_ssf_many(void* p, const char* buf, long long len,
                       const char* ind_name, int ind_len,
                       const char* obj_name, int obj_len, double uniq_rate,
                       int* errors_out, int* fallback_off,
                       int* fallback_len, int fallback_cap,
                       int* nfall_out) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  std::string_view ind(ind_name, ind_len), obj(obj_name, obj_len);
  long long pos = 0;
  int ok = 0, errs = 0, nfall = 0;
  while (pos + 4 <= len) {
    uint32_t flen;
    std::memcpy(&flen, buf + pos, 4);
    pos += 4;
    if (flen > static_cast<uint64_t>(len - pos)) {
      ++errs;
      break;
    }
    if (flen == 0) {
      // empty datagram: proto3 decodes it as an all-default span, which
      // would count as processed; match the single-packet path's
      // empty-packet parse error (server.py handle_trace_packet)
      ++errs;
      continue;
    }
    int rc = ingest_ssf_span(ctx, std::string_view(buf + pos, flen), ind,
                             obj, uniq_rate);
    if (rc == 1) {
      ++ok;
    } else if (rc == 0) {
      ++errs;
    } else if (nfall < fallback_cap) {
      fallback_off[nfall] = static_cast<int>(pos);
      fallback_len[nfall] = static_cast<int>(flen);
      ++nfall;
    } else {
      ++errs;  // fallback list full; count as error rather than drop silently
    }
    pos += flen;
  }
  *errors_out = errs;
  *nfall_out = nfall;
  return ok;
}

long long vn_ssf_spans(void* p) { return static_cast<Ctx*>(p)->ssf_spans; }
long long vn_ssf_invalid(void* p) {
  return static_cast<Ctx*>(p)->ssf_invalid;
}

// Drain the per-service span counters as "service\tcount\n" lines.
// Output beyond cap stays buffered for the next call (like
// vn_drain_other) — truncating after clearing would lose counts and
// could hand Python a cut mid-line.
//
// CAP CONTRACT: cap must be >= one full line (service names are
// truncated to 256 bytes at ingest, so 256 + 1 tab + 20 digit count +
// newline = 278; callers must pass cap >= 512). With a smaller cap a
// line that doesn't fit returns 0 while data stays buffered, and a
// `while n > 0` drain loop would stall until the next flush.
int vn_drain_ssf_services(void* p, char* buf, int cap) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  for (const auto& e : ctx->ssf_services) {
    ctx->ssf_services_out.append(e.first);
    ctx->ssf_services_out.push_back('\t');
    ctx->ssf_services_out.append(std::to_string(e.second));
    ctx->ssf_services_out.push_back('\n');
  }
  ctx->ssf_services.clear();
  // cut on a line boundary so the consumer never sees a partial record
  // (cap clamped: a negative cap must not become a huge memcpy size)
  size_t n = cap < 0 ? 0
                     : std::min(static_cast<size_t>(cap),
                                ctx->ssf_services_out.size());
  while (n > 0 && ctx->ssf_services_out[n - 1] != '\n') --n;
  std::memcpy(buf, ctx->ssf_services_out.data(), n);
  ctx->ssf_services_out.erase(0, n);
  return static_cast<int>(n);
}

// Drain the buffered event/service-check lines (newline separated).
// Cuts on a line boundary like vn_drain_ssf_services so a full buffer
// never severs a record across two drains.
//
// CAP CONTRACT: cap should be >= metric_max_length + 1 (events are
// length-capped at ingest); an oversize first record is dropped whole,
// counted in vn_errors, and the drain continues with the records
// behind it — so a `while n > 0` loop never stalls on one bad record.
int vn_drain_other(void* p, char* buf, int cap) {
  Ctx* ctx = static_cast<Ctx*>(p);
  std::lock_guard<std::recursive_mutex> ctx_guard(ctx->mu);
  size_t n;
  for (;;) {
    n = cap < 0 ? 0
                : std::min(static_cast<size_t>(cap), ctx->other_lines.size());
    while (n > 0 && ctx->other_lines[n - 1] != '\n') --n;
    if (n == 0 && cap > 0 && !ctx->other_lines.empty()) {
      // degenerate: first record alone exceeds the caller's buffer — drop
      // it whole (counted as an error so the loss is observable) and
      // retry, so complete records queued behind it still drain this call
      // rather than emitting a severed fragment the consumer would
      // misparse as two records
      size_t nl = ctx->other_lines.find('\n');
      ctx->other_lines.erase(
          0, nl == std::string::npos ? ctx->other_lines.size() : nl + 1);
      ++ctx->errors;
      continue;
    }
    break;
  }
  std::memcpy(buf, ctx->other_lines.data(), n);
  ctx->other_lines.erase(0, n);
  return static_cast<int>(n);
}

}  // extern "C"
